"""Probability distributions: Uniform, Normal, Categorical.

Reference parity: python/paddle/distribution.py (Distribution:41, Uniform:168,
Normal:390, Categorical:640). TPU-native design: distributions are pure-function
wrappers over jnp; `sample` draws from the framework's stateful Generator (an
explicit jax PRNG key under the hood, core/generator.py) so sampling composes
with `paddle.seed` determinism, and every density op flows through the autodiff
dispatcher so `log_prob(value).backward()` works like any other op.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .core.dispatch import apply
from .core.generator import default_generator
from .core.tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _t(x, dtype="float32"):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x, dtype=dtype))


def _key():
    return default_generator().split()


class Distribution:
    """Abstract base (reference distribution.py:41)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) with broadcastable endpoints (reference distribution.py:168)."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        self.name = name or "Uniform"

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        lo, hi = self.low._data, self.high._data
        bshape = shape + tuple(np.broadcast_shapes(lo.shape, hi.shape))
        u = jax.random.uniform(_key(), bshape, dtype=lo.dtype)
        return Tensor(lo + u * (hi - lo))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return apply(fn, value, self.low, self.high)

    def probs(self, value):
        value = _t(value)

        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, 1.0 / (hi - lo), 0.0)

        return apply(fn, value, self.low, self.high)

    def entropy(self):
        return apply(lambda lo, hi: jnp.log(hi - lo), self.low, self.high)


class Normal(Distribution):
    """N(loc, scale) (reference distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self.name = name or "Normal"

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        mu, sig = self.loc._data, self.scale._data
        bshape = shape + tuple(np.broadcast_shapes(mu.shape, sig.shape))
        z = jax.random.normal(_key(), bshape, dtype=mu.dtype)
        return Tensor(mu + z * sig)

    def log_prob(self, value):
        value = _t(value)

        def fn(v, mu, sig):
            var = sig * sig
            return -((v - mu) ** 2) / (2.0 * var) - jnp.log(sig) - 0.5 * math.log(2.0 * math.pi)

        return apply(fn, value, self.loc, self.scale)

    def entropy(self):
        return apply(
            lambda mu, sig: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2.0 * math.pi) + jnp.log(sig),
                np.broadcast_shapes(mu.shape, sig.shape),
            ),
            self.loc,
            self.scale,
        )

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (reference distribution.py:595)."""

        def fn(mu0, sig0, mu1, sig1):
            var_ratio = (sig0 / sig1) ** 2
            t1 = ((mu0 - mu1) / sig1) ** 2
            return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))

        return apply(fn, self.loc, self.scale, other.loc, other.scale)


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference distribution.py:640)."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        self.name = name or "Categorical"

    def _log_pmf(self):
        return apply(lambda lg: jax.nn.log_softmax(lg, axis=-1), self.logits)

    def sample(self, shape=()):
        shape = tuple(shape)
        batch = self.logits._data.shape[:-1]
        return Tensor(
            jax.random.categorical(_key(), self.logits._data, axis=-1, shape=shape + batch)
        )

    def log_prob(self, value):
        value = _t(value)
        lp = self._log_pmf()
        return apply(
            lambda l, v: jnp.take_along_axis(l, v[..., None].astype(jnp.int32), axis=-1)[..., 0],
            lp,
            value,
        )

    def probs(self, value):
        return apply(jnp.exp, self.log_prob(value))

    def entropy(self):
        lp = self._log_pmf()
        return apply(lambda l: -jnp.sum(jnp.exp(l) * l, axis=-1), lp)

    def kl_divergence(self, other):
        lp, lq = self._log_pmf(), other._log_pmf()
        return apply(lambda a, b: jnp.sum(jnp.exp(a) * (a - b), axis=-1), lp, lq)
