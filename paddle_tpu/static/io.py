"""Inference model export/import.

Reference parity: python/paddle/static/io.py save/load_inference_model (+
fluid/io.py, pybind inference AnalysisPredictor consumption).
TPU-native design: export = params npz + StableHLO text of the jitted forward —
consumable by any XLA runtime (the inference/predictor.py AOT path loads it back).
"""
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, program=None, layer=None, **kwargs):
    """When `layer` is given (the TPU-native path), exports StableHLO + params."""
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    if layer is not None:
        params = {n: np.asarray(t._data) for n, t in layer.state_dict().items()}
        np.savez(path_prefix + ".pdiparams.npz", **params)

        def pure(params_d, *args):
            wrapped = [Tensor(a) for a in args]
            from ..core.tape import global_tape

            named = dict(layer.named_parameters())
            named.update(dict(layer.named_buffers()))
            saved = {n: t._data for n, t in named.items()}
            try:
                for n, v in params_d.items():
                    if n in named:
                        named[n]._data = v
                with global_tape().pause():
                    out = layer.forward(*wrapped)
            finally:
                for n, t in named.items():
                    t._data = saved[n]
            return jax.tree_util.tree_map(lambda v: v._data if isinstance(v, Tensor) else v, out,
                                          is_leaf=lambda v: isinstance(v, Tensor))

        def _arg_structs(symbolic):
            """None/-1 dims become export-time symbolic dims (batch-
            polymorphic artifact); `symbolic=False` pins them to 1.

            Leading (dim-0, batch) dynamic dims SHARE one symbol — models
            that relate two inputs along batch (loss(input, label)) need the
            equality constraint; other dynamic dims get distinct symbols."""
            structs, n_sym, batch_sym = [], 0, None
            for v in feed_vars:
                dims = []
                for pos, s in enumerate(v.shape):
                    if s is None or (isinstance(s, int) and s < 0):
                        if not symbolic:
                            dims.append(1)
                        elif pos == 0:
                            if batch_sym is None:
                                (batch_sym,) = jax.export.symbolic_shape("b")
                            dims.append(batch_sym)
                        else:
                            (d,) = jax.export.symbolic_shape(f"d{n_sym}")
                            n_sym += 1
                            dims.append(d)
                    else:
                        dims.append(s)
                structs.append(jax.ShapeDtypeStruct(tuple(dims), v.dtype))
            return structs

        params_j = {k: jnp.asarray(v) for k, v in params.items()}
        jitted = jax.jit(pure)
        # executable round-trip artifact (jax.export): the AOT predictor and
        # jit.load run this without the original python Layer — the
        # deployment-grade path. serialize fully before touching disk, write
        # tmp + rename so a crash can never leave a truncated artifact.
        exported = None
        try:
            exported = jax.export.export(jitted)(params_j,
                                                 *_arg_structs(True))
        except Exception as e_sym:
            try:
                exported = jax.export.export(jitted)(params_j,
                                                     *_arg_structs(False))
                import warnings

                warnings.warn(
                    f"symbolic-batch export failed ({e_sym}); exported with "
                    "dynamic dims pinned to 1 — loads serve that shape only")
            except Exception as e:
                import warnings

                warnings.warn(f"jax.export serialization unavailable ({e}); "
                              "saving StableHLO text + params only")
        wrote_artifact = False
        if exported is not None:
            try:
                blob = exported.serialize()
            except Exception as e:
                import warnings

                warnings.warn(f"jax.export serialization failed ({e}); "
                              "saving StableHLO text + params only")
            else:
                tmp = path_prefix + ".pdmodel.jaxexport.tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path_prefix + ".pdmodel.jaxexport")
                wrote_artifact = True
        if exported is not None:
            hlo_text = str(exported.mlir_module())  # no second trace
        else:
            hlo_text = jitted.lower(params_j, *_arg_structs(False)).as_text()
        with open(path_prefix + ".pdmodel.stablehlo", "w") as f:
            f.write(hlo_text)
        with open(path_prefix + ".pdmodel.meta", "wb") as f:
            pickle.dump({"feed_shapes": [tuple(v.shape) for v in feed_vars],
                         "feed_dtypes": [str(v.dtype) for v in feed_vars]}, f)
        return {"path": path_prefix, "exported": wrote_artifact}
    raise NotImplementedError("save_inference_model requires layer= in the TPU build")


def load_inference_model(path_prefix, executor=None, **kwargs):
    data = np.load(path_prefix + ".pdiparams.npz")
    params = {k: data[k] for k in data.files}
    with open(path_prefix + ".pdmodel.meta", "rb") as f:
        meta = pickle.load(f)
    with open(path_prefix + ".pdmodel.stablehlo") as f:
        hlo_text = f.read()
    return params, meta, hlo_text


def _load_exported(path_prefix):
    """Deserialize the jax.export artifact + params (shared by jit.load and
    load_aot_predictor)."""
    with open(path_prefix + ".pdmodel.jaxexport", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    data = np.load(path_prefix + ".pdiparams.npz")
    params = {k: data[k] for k in data.files}
    return exported, params


def load_aot_predictor(path_prefix):
    """AOT predictor from the serialized jax.export artifact: a callable
    `fn(*inputs) -> outputs` bound to the saved params — no python Layer or
    re-trace needed (the AnalysisPredictor-on-saved-model analog)."""
    exported, raw = _load_exported(path_prefix)
    params = {k: jnp.asarray(v) for k, v in raw.items()}

    def predict(*inputs):
        arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                for a in inputs]
        out = exported.call(params, *arrs)
        return jax.tree_util.tree_map(Tensor, out)

    return predict


def save(program, model_path, protocol=4, **configs):
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(program, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)
