"""Inference model export/import.

Reference parity: python/paddle/static/io.py save/load_inference_model (the
Program-path signature at static/io.py:442 `(path_prefix, feed_vars,
fetch_vars, executor)` and the layer-based jit path), legacy fluid/io.py:1199,
plus pybind inference AnalysisPredictor consumption.

TPU-native design: export = params npz + a serialized `jax.export` artifact
(+ StableHLO text) of the jitted forward — consumable by any XLA runtime
(inference/predictor.py AOT path loads it back without python model code).
Both entry paths converge here:
  * layer=Layer       — trace the dygraph Layer's forward.
  * (feed, fetch, exe) — replay the recorded static Program's op slice
                         (static/__init__.py Executor's compile path).
"""
import os
import pickle
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


_BF16_KEY = "__bf16_names__"


def _savez_params(path, params):
    """np.savez with bfloat16 support: numpy serializes ml_dtypes.bfloat16 as
    an opaque V2 void dtype, so bf16 arrays are stored as uint16 bit-views
    plus a name manifest under _BF16_KEY (consumed by _load_params_npz)."""
    import ml_dtypes

    out, bf16 = {}, []
    for k, v in params.items():
        v = np.asarray(v)
        if v.dtype == ml_dtypes.bfloat16:
            out[k] = v.view(np.uint16)
            bf16.append(k)
        else:
            out[k] = v
    if bf16:
        out[_BF16_KEY] = np.array(bf16)
    np.savez(path, **out)


def _load_params_npz(path):
    import ml_dtypes

    data = np.load(path)
    bf16 = set(np.asarray(data[_BF16_KEY]).tolist()) \
        if _BF16_KEY in data.files else set()
    return {k: (np.asarray(data[k]).view(ml_dtypes.bfloat16)
                if k in bf16 else data[k])
            for k in data.files if k != _BF16_KEY}


def _arg_structs(shapes, dtypes, symbolic):
    """Build ShapeDtypeStructs for export. None/-1 dims become export-time
    symbolic dims (batch-polymorphic artifact); `symbolic=False` pins them
    to 1.

    Leading (dim-0, batch) dynamic dims SHARE one symbol — models that
    relate two inputs along batch (loss(input, label)) need the equality
    constraint; other dynamic dims get distinct symbols."""
    structs, n_sym, batch_sym = [], 0, None
    for shape, dtype in zip(shapes, dtypes):
        dims = []
        for pos, s in enumerate(shape):
            if s is None or (isinstance(s, int) and s < 0):
                if not symbolic:
                    dims.append(1)
                elif pos == 0:
                    if batch_sym is None:
                        (batch_sym,) = jax.export.symbolic_shape("b")
                    dims.append(batch_sym)
                else:
                    (d,) = jax.export.symbolic_shape(f"d{n_sym}")
                    n_sym += 1
                    dims.append(d)
            else:
                dims.append(s)
        structs.append(jax.ShapeDtypeStruct(tuple(dims), dtype))
    return structs


def _write_export_artifact(pure, params, shapes, dtypes, path_prefix):
    """Shared export tail: serialize `pure(params, *feeds)` as a durable
    jax.export artifact + StableHLO text + params npz + meta. Serializes
    fully before touching disk and writes tmp + rename so a crash can never
    leave a truncated artifact. Returns whether the executable artifact was
    written (StableHLO text always is)."""
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    _savez_params(path_prefix + ".pdiparams.npz", params)
    params_j = {k: jnp.asarray(v) for k, v in params.items()}
    jitted = jax.jit(pure)
    exported = None
    try:
        exported = jax.export.export(jitted)(
            params_j, *_arg_structs(shapes, dtypes, True))
    except Exception as e_sym:
        try:
            exported = jax.export.export(jitted)(
                params_j, *_arg_structs(shapes, dtypes, False))
            warnings.warn(
                f"symbolic-batch export failed ({e_sym}); exported with "
                "dynamic dims pinned to 1 — loads serve that shape only")
        except Exception as e:
            warnings.warn(f"jax.export serialization unavailable ({e}); "
                          "saving StableHLO text + params only")
    wrote_artifact = False
    if exported is not None:
        try:
            blob = exported.serialize()
        except Exception as e:
            warnings.warn(f"jax.export serialization failed ({e}); "
                          "saving StableHLO text + params only")
        else:
            tmp = path_prefix + ".pdmodel.jaxexport.tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path_prefix + ".pdmodel.jaxexport")
            wrote_artifact = True
    if exported is not None:
        hlo_text = str(exported.mlir_module())  # no second trace
    else:
        hlo_text = jitted.lower(
            params_j, *_arg_structs(shapes, dtypes, False)).as_text()
    with open(path_prefix + ".pdmodel.stablehlo", "w") as f:
        f.write(hlo_text)
    with open(path_prefix + ".pdmodel.meta", "wb") as f:
        pickle.dump({"feed_shapes": [tuple(s) for s in shapes],
                     "feed_dtypes": [str(d) for d in dtypes]}, f)
    return wrote_artifact


def layer_pure_fn(layer, force_eval=False):
    """Pure `(params_dict, *arrays) -> forward output` view of a Layer —
    the substitute-params/trace/restore dance shared by jit.save /
    save_inference_model (here) and paddle.onnx.export. force_eval=True
    additionally pins train=False for the trace (inference export); the
    jit.save path keeps the layer's current mode (r3 behavior)."""

    def pure(params_d, *args):
        wrapped = [Tensor(a) for a in args]
        from ..core.tape import global_tape

        named = dict(layer.named_parameters())
        named.update(dict(layer.named_buffers()))
        saved = {n: t._data for n, t in named.items()}
        saved_modes = ([(l, l.training)
                        for l in [layer] + layer.sublayers()]
                       if force_eval else [])
        try:
            for l, _ in saved_modes:
                l.training = False
            for n, v in params_d.items():
                if n in named:
                    named[n]._data = v
            with global_tape().pause():
                out = layer.forward(*wrapped)
        finally:
            for n, t in named.items():
                t._data = saved[n]
            for l, m in saved_modes:
                l.training = m
        return jax.tree_util.tree_map(
            lambda v: v._data if isinstance(v, Tensor) else v, out,
            is_leaf=lambda v: isinstance(v, Tensor))

    return pure


def _save_layer(path_prefix, feed_vars, layer):
    params = {n: np.asarray(t._data) for n, t in layer.state_dict().items()}
    pure = layer_pure_fn(layer)

    shapes = [tuple(v.shape) for v in feed_vars]
    dtypes = [v.dtype for v in feed_vars]
    wrote = _write_export_artifact(pure, params, shapes, dtypes, path_prefix)
    return {"path": path_prefix, "exported": wrote}


def _save_program(path_prefix, feed_vars, fetch_vars, program):
    """Program path (reference static/io.py:442): export the recorded static
    Program's backward slice as a pure (params, *feeds) -> fetches function.
    Mirrors static/__init__.py Executor._compile's inference path, but traced
    for AOT export instead of jit-per-feed-signature."""
    from . import _slice_ops

    if not program.ops:
        raise ValueError(
            "save_inference_model: the program records no ops — build it "
            "from static.data placeholders under program_guard first")
    program._ensure_scope()

    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetches = (fetch_vars if isinstance(fetch_vars, (list, tuple))
               else [fetch_vars])

    # resolve feed tensors -> placeholder (name, var id, declared shape)
    ph_by_vid = {vid: name for name, vid in program.placeholders.items()}
    feed_ids, shapes, dtypes = [], [], []
    for v in feeds:
        vid = program._resolve_var(v) if isinstance(v, Tensor) else None
        if vid is None and isinstance(v, str):
            vid = program.placeholders.get(v)
        if vid is None or vid not in ph_by_vid:
            raise ValueError(
                f"feed var {getattr(v, 'name', v)!r} is not a static.data "
                "placeholder of this program")
        name = ph_by_vid[vid]
        feed_ids.append(vid)
        shapes.append(program.placeholder_shapes[name])
        dtypes.append(program.vars[vid].dtype)

    fetch_ids = []
    for v in fetches:
        vid = program._resolve_var(v) if isinstance(v, Tensor) else None
        if vid is None:
            raise ValueError(
                f"fetch var {getattr(v, 'name', v)!r} was not built in this "
                "program")
        fetch_ids.append(vid)

    ops = _slice_ops(program, fetch_ids)

    # validate the slice is fully served by feeds + params before tracing
    bound = set(feed_ids) | set(program.params)
    for op in ops:
        for spec in op.arg_specs:
            if spec[0] == "var" and spec[1] not in bound:
                missing = ph_by_vid.get(spec[1])
                if missing is not None:
                    raise ValueError(
                        f"placeholder '{missing}' is required by the fetch "
                        "targets but is not among feed_vars")
                raise ValueError("fetch targets reference a var with no "
                                 "producer (built in a different program?)")
        bound |= set(op.out_ids)
    for fid in fetch_ids:
        if fid not in bound:
            raise ValueError("fetch target has no producer in this program")

    params = {n: np.asarray(program._scope["params"][n])
              for n in program.param_names}
    params_map = dict(program.params)

    def pure(params_d, *feed_arrays):
        env = dict(zip(feed_ids, feed_arrays))
        for vid, name in params_map.items():
            env[vid] = params_d[name]
        for op in ops:
            vals = [env[s[1]] if s[0] == "var" else s[1]
                    for s in op.arg_specs]
            out = op.fn(*vals, **op.kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for oid, o in zip(op.out_ids, outs):
                env[oid] = o
        return [env[i] for i in fetch_ids]

    wrote = _write_export_artifact(pure, params, shapes, dtypes, path_prefix)
    return {"path": path_prefix, "exported": wrote}


def save_inference_model(path_prefix, feed_vars=None, fetch_vars=None,
                         executor=None, program=None, layer=None, **kwargs):
    """Both reference signatures converge on the same AOT artifact:

    * `save_inference_model(path, feed_vars, fetch_vars, exe)` — the static
      Program path (reference python/paddle/static/io.py:442): exports the
      recorded default (or `program=`) Program's inference slice.
    * `save_inference_model(path, feed_vars, ..., layer=layer)` — the
      TPU-native dygraph path: traces the Layer's forward.
    """
    if layer is not None:
        os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
        return _save_layer(path_prefix, feed_vars, layer)
    from . import Program, default_main_program

    prog = program or default_main_program()
    if isinstance(prog, Program):
        if prog._optimizer is not None:
            prog = prog.clone(for_test=True)  # never export the train step
        return _save_program(path_prefix, feed_vars, fetch_vars, prog)
    raise TypeError(
        "save_inference_model: pass layer= (dygraph) or build a static "
        "Program (program_guard + static.data) before exporting")


def load_inference_model(path_prefix, executor=None, **kwargs):
    params = _load_params_npz(path_prefix + ".pdiparams.npz")
    with open(path_prefix + ".pdmodel.meta", "rb") as f:
        meta = pickle.load(f)
    with open(path_prefix + ".pdmodel.stablehlo") as f:
        hlo_text = f.read()
    return params, meta, hlo_text


def _load_exported(path_prefix):
    """Deserialize the jax.export artifact + params (shared by jit.load and
    load_aot_predictor). Params are cast back to the dtypes the exported
    signature expects, so a bf16-converted params file
    (inference.convert_to_mixed_precision) still serves an f32 artifact."""
    with open(path_prefix + ".pdmodel.jaxexport", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    params = _load_params_npz(path_prefix + ".pdiparams.npz")
    want = None
    try:
        # Exported.in_tree is the treedef of (args, kwargs); args[0] is the
        # params dict of avals for artifacts written by this module
        tree = jax.tree_util.tree_unflatten(exported.in_tree,
                                            list(exported.in_avals))
        args = tree[0] if isinstance(tree, tuple) and len(tree) == 2 else tree
        if isinstance(args, (list, tuple)) and args and \
                isinstance(args[0], dict):
            want = args[0]
    except Exception:
        want = None
    if want:
        params = {k: (v.astype(want[k].dtype)
                      if k in want and v.dtype != want[k].dtype else v)
                  for k, v in params.items()}
    return exported, params


def load_aot_predictor(path_prefix):
    """AOT predictor from the serialized jax.export artifact: a callable
    `fn(*inputs) -> outputs` bound to the saved params — no python Layer or
    re-trace needed (the AnalysisPredictor-on-saved-model analog)."""
    exported, raw = _load_exported(path_prefix)
    params = {k: jnp.asarray(v) for k, v in raw.items()}

    def predict(*inputs):
        arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                for a in inputs]
        out = exported.call(params, *arrs)
        return jax.tree_util.tree_map(Tensor, out)

    return predict


def save(program, model_path, protocol=4, **configs):
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(program, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)
