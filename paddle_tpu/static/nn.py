"""paddle.static.nn functional shims (fc, conv2d, batch_norm ...) — thin wrappers over
paddle_tpu.nn layers for static-style code (python/paddle/static/nn/__init__.py parity)."""
from .. import nn as _nn


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    from ..tensor.manipulation import flatten

    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= s
    layer = _nn.Linear(in_features, size, weight_attr, bias_attr)
    x2 = flatten(x, num_flatten_dims, -1) if x.ndim > num_flatten_dims + 1 else x
    out = layer(x2)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1,
           param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
    in_c = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _nn.Conv2D(in_c, num_filters, filter_size, stride, padding, dilation,
                       groups or 1, weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None, **kw):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.BatchNorm2D(c, momentum, epsilon, param_attr, bias_attr, data_layout)
    layer.training = not is_test
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out
