"""paddle.static.nn functional shims (fc, conv2d, batch_norm, control flow ...)
— thin wrappers over paddle_tpu.nn layers for static-style code
(python/paddle/static/nn/__init__.py parity: the reference's 22-name surface).

Control flow (cond/case/switch_case/while_loop) dispatches through the
dy2static runtime converters: host branches for concrete predicates,
lax.cond/lax.switch/lax.while_loop under a trace. In a recorded static
Program, data-dependent control flow should live inside an @to_static
function (the record-replay executor records eager ops; a build-time python
branch would bake one side)."""
from .. import nn as _nn


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    from ..tensor.manipulation import flatten

    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= s
    layer = _nn.Linear(in_features, size, weight_attr, bias_attr)
    x2 = flatten(x, num_flatten_dims, -1) if x.ndim > num_flatten_dims + 1 else x
    out = layer(x2)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1,
           param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
    in_c = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _nn.Conv2D(in_c, num_filters, filter_size, stride, padding, dilation,
                       groups or 1, weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None, **kw):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.BatchNorm2D(c, momentum, epsilon, param_attr, bias_attr, data_layout)
    layer.training = not is_test
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    in_c = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    k = _derive_transpose_kernel(filter_size, output_size, input.shape[-1],
                                 stride, padding, dilation)
    layer = _nn.Conv2DTranspose(in_c, num_filters, k,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups or 1,
                                weight_attr=param_attr, bias_attr=bias_attr,
                                data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(_nn.functional, act)(out) if act else out


def _derive_transpose_kernel(filter_size, output_size, in_size, stride,
                             padding, dilation):
    """Reference conv*_transpose derives the kernel from output_size when
    filter_size is None: out = (in-1)*stride - 2*pad + dilation*(k-1) + 1."""
    if filter_size is not None:
        return filter_size
    if output_size is None:
        raise ValueError(
            "conv transpose: one of filter_size / output_size is required")
    o = output_size[-1] if isinstance(output_size, (list, tuple)) \
        else output_size
    s = stride[-1] if isinstance(stride, (list, tuple)) else stride
    p = padding[-1] if isinstance(padding, (list, tuple)) else padding
    d = dilation[-1] if isinstance(dilation, (list, tuple)) else dilation
    k = (o - (in_size - 1) * s + 2 * p - 1) // d + 1
    if k < 1:
        raise ValueError(
            f"conv transpose: output_size {o} unreachable from input "
            f"{in_size} with stride {s}/padding {p}")
    return k


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCDHW"):
    in_c = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _nn.Conv3D(in_c, num_filters, filter_size, stride, padding,
                       dilation, groups or 1, weight_attr=param_attr,
                       bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    return getattr(_nn.functional, act)(out) if act else out


def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCDHW"):
    in_c = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    k = _derive_transpose_kernel(filter_size, output_size, input.shape[-1],
                                 stride, padding, dilation)
    layer = _nn.Conv3DTranspose(in_c, num_filters, k,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups or 1,
                                weight_attr=param_attr, bias_attr=bias_attr,
                                data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(_nn.functional, act)(out) if act else out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """static.nn.embedding parity: creates the table parameter in place.
    is_sparse/is_distributed are accepted (XLA gathers are already sparse
    lookups; the PS path owns truly distributed tables)."""
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32", **kwargs):
    """fluid.contrib sparse_embedding (PS huge-table lookup): dense on TPU —
    the distributed PS path serves real sparse tables (distributed/ps)."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    import paddle_tpu as _paddle

    return _paddle.create_parameter(
        shape, dtype, name=name, attr=attr, is_bias=is_bias,
        default_initializer=default_initializer)


def crf_decoding(input, transition, label=None, length=None, name=None):
    """crf_decoding_op parity: viterbi argmax path under the linear-chain CRF
    (text/viterbi.py). `transition` is the [T+2, T] parameter learned by
    text.linear_chain_crf."""
    from ..text.viterbi import crf_decoding as _crf

    return _crf(input, transition, length=length, label=label)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, **kwargs):
    from ..core.tensor import Tensor
    import numpy as np
    import jax.numpy as jnp

    c = input.shape[-1]
    bsz = Tensor(jnp.full((c,), 1e4, jnp.float32))
    bsum = Tensor(jnp.zeros((c,), jnp.float32))
    bsq = Tensor(jnp.full((c,), 1e4, jnp.float32))
    out = _nn.functional.data_norm(input, bsz, bsum, bsq)
    return getattr(_nn.functional, act)(out) if act else out


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import DeformConv2D as _DC

    layer = _DC(input.shape[1], num_filters, filter_size, stride, padding,
                dilation, deformable_groups, groups or 1,
                weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input, offset, mask=mask)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _nn.GroupNorm(groups, c, epsilon, param_attr, bias_attr)
    out = layer(input)
    return getattr(_nn.functional, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    c = input.shape[1]
    layer = _nn.InstanceNorm2D(c, epsilon=epsilon, weight_attr=param_attr,
                               bias_attr=bias_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = list(input.shape[begin_norm_axis:])
    layer = _nn.LayerNorm(shape, epsilon,
                          param_attr if scale else False,
                          bias_attr if shift else False)
    out = layer(input)
    return getattr(_nn.functional, act)(out) if act else out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    dim = input.shape[-1]
    w = create_parameter([num_total_classes, dim], attr=param_attr)
    b = None if bias_attr is False else create_parameter(
        [num_total_classes], attr=bias_attr, is_bias=True)
    return _nn.functional.nce(input, label, w, bias=b,
                              num_total_classes=num_total_classes,
                              num_neg_samples=num_neg_samples or 10,
                              sampler=sampler, custom_dist=custom_dist,
                              seed=seed, sample_weight=sample_weight)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    from ..nn import initializer as I

    if mode == "all":
        n = [1]
    elif mode == "channel":
        n = [x.shape[1] if data_format == "NCHW" else x.shape[-1]]
    else:  # element: one alpha per non-batch element
        n = list(x.shape[1:])
    alpha = create_parameter(n, attr=param_attr,
                             default_initializer=I.Constant(0.25))
    if mode in ("all", "channel"):
        return _nn.functional.prelu(x, alpha, data_format=data_format)
    # element mode: functional.prelu only reshapes per-channel; apply the
    # per-element alpha directly (broadcast over batch)
    import jax.numpy as jnp

    from ..core.dispatch import apply

    return apply(lambda v, a: jnp.where(v >= 0, v, a[None] * v), x, alpha)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from . import py_func as _pf

    return _pf(func, x, out, backward_func=backward_func,
               skip_vars_in_backward_input=skip_vars_in_backward_input)


def row_conv(input, future_context_size, param_attr=None, act=None):
    c = input.shape[-1]
    w = create_parameter([future_context_size + 1, c], attr=param_attr)
    out = _nn.functional.row_conv(input, w)
    return getattr(_nn.functional, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """spectral_norm_op parity: normalize `weight` by its largest singular
    value, estimated with `power_iters` rounds of power iteration."""
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..core.tensor import Tensor

    def fn(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), w.dtype) / jnp.sqrt(mat.shape[0])
        for _ in range(max(1, power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return w / (sigma + eps)

    return apply(fn, weight if isinstance(weight, Tensor) else Tensor(weight))


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    w = create_parameter([size, x.shape[-1], y.shape[-1]], attr=param_attr)
    b = None if bias_attr is False else create_parameter(
        [size], attr=bias_attr, is_bias=True)
    out = _nn.functional.bilinear_tensor_product(x, y, w, b)
    return getattr(_nn.functional, act)(out) if act else out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (fluid/layers/detection.py multi_box_head parity):
    per feature map, a conv each for loc (priors*4) and conf
    (priors*num_classes) plus its prior boxes; outputs concatenated over maps.
    Returns (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes [P, 4],
    variances [P, 4])."""
    import numpy as np

    from ..tensor.manipulation import concat, reshape, transpose
    from ..vision.ops import prior_box as _prior_box

    n_in = len(inputs)
    if min_sizes is None:
        # the reference's min/max_ratio schedule
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_in - 2)) if n_in > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_in - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_in - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        mx = (max_sizes[i] if isinstance(max_sizes[i], (list, tuple))
              else [max_sizes[i]]) if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        st = None
        if steps is not None:
            st = steps[i] if isinstance(steps[i], (list, tuple)) \
                else [steps[i], steps[i]]
        elif step_w is not None and step_h is not None:
            st = [step_w[i], step_h[i]]
        box, var = _prior_box(feat, image, min_sizes=ms, max_sizes=mx,
                              aspect_ratios=ar, variance=list(variance),
                              flip=flip, clip=clip,
                              steps=st or [0.0, 0.0], offset=offset)
        n_priors_cell = box.shape[2]
        boxes_all.append(reshape(box, [-1, 4]))
        vars_all.append(reshape(var, [-1, 4]))
        loc = conv2d(feat, n_priors_cell * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(feat, n_priors_cell * num_classes, kernel_size,
                      stride=stride, padding=pad)
        locs.append(reshape(transpose(loc, [0, 2, 3, 1]),
                            [loc.shape[0], -1, 4]))
        confs.append(reshape(transpose(conf, [0, 2, 3, 1]),
                             [conf.shape[0], -1, num_classes]))
    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(boxes_all, axis=0), concat(vars_all, axis=0))


# -- control flow (fluid/layers/control_flow.py parity) ----------------------

def cond(pred, true_fn=None, false_fn=None, name=None):
    """lax.cond under a trace; a host branch for concrete predicates.
    A None branch (permitted by the reference) is a no-op returning None —
    valid only when the other branch also returns nothing."""
    from ..jit.dy2static import convert_ifelse

    def _norm(f):
        if f is None:
            return lambda _s: ()

        def g(_s):
            r = f()
            return () if r is None else r  # side-effect-only branches

        return g

    out = convert_ifelse(pred, _norm(true_fn), _norm(false_fn))
    if isinstance(out, tuple) and len(out) == 0:
        return None
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out


def case(pred_fn_pairs, default=None, name=None):
    """First-true-pred dispatch, lowered to a nested cond chain."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs may not be empty")
    (pred, fn), rest = pred_fn_pairs[0], pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return fn()
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default=default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """lax.switch under a trace; host dispatch for concrete indices."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = [(i, f) if not isinstance(f, (tuple, list)) else tuple(f)
                 for i, f in enumerate(branch_fns)]
    keys = [k for k, _ in pairs]
    fns = [f for _, f in pairs]
    idx_raw = branch_index._data if isinstance(branch_index, Tensor) \
        else branch_index
    if not isinstance(idx_raw, jax.core.Tracer):
        i = int(np.asarray(idx_raw))
        if i in keys:
            return fns[keys.index(i)]()
        if default is None:
            return fns[-1]()  # reference: last branch is the fallback
        return default()
    # traced: dense lax.switch over the key range (+1 slot for default)
    all_fns = fns + [default if default is not None else fns[-1]]
    lut = np.full(max(keys) + 1, len(all_fns) - 1, np.int32)
    for pos, k in enumerate(keys):
        lut[k] = pos
    sel = jnp.clip(jnp.asarray(idx_raw).astype(jnp.int32), 0, max(keys))
    sel = jnp.asarray(lut)[sel]
    sel = jnp.where(
        (jnp.asarray(idx_raw) < 0) | (jnp.asarray(idx_raw) > max(keys)),
        len(all_fns) - 1, sel)

    def wrap(f):
        def g(_):
            o = f()
            return tuple(v._data if isinstance(v, Tensor) else v
                         for v in (o if isinstance(o, tuple) else (o,)))
        return g

    res = jax.lax.switch(sel, [wrap(f) for f in all_fns], 0)
    res = tuple(Tensor(r) for r in res)
    return res[0] if len(res) == 1 else res


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """fluid.layers.while_loop parity: cond/body take *loop_vars; runs
    lax.while_loop when the condition is traced, a host loop otherwise."""
    from ..jit.dy2static import convert_while_loop

    def _cond(carry):
        return cond(*carry)

    def _body(carry):
        out = body(*carry)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    res = convert_while_loop(_cond, _body, tuple(loop_vars))
    return list(res)
