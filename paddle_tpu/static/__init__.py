"""paddle.static parity (python/paddle/static/__init__.py).

Reference parity: the Program/Executor static-graph world (fluid/framework.py:4174
Program, fluid/executor.py:475 Executor). TPU-native design: a "Program" is a recorded
python callable + captured parameter state; Executor.run jit-compiles it. This keeps the
paddle.static API shape (enable_static, data, program_guard, Executor) while the real
compilation is jax.jit — there is no separate graph IR to interpret.
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from ..jit import InputSpec  # noqa: F401

_STATIC_MODE = [False]


def enable_static():
    _STATIC_MODE[0] = True


def disable_static():
    _STATIC_MODE[0] = False


def in_static_mode():
    return _STATIC_MODE[0]


def in_dynamic_mode():
    return not _STATIC_MODE[0]


class Program:
    """Deferred-execution program: a list of (fn, inputs, outputs) build steps.

    The fluid Program/Block/Op IR (framework.py:978-4174) collapses to: the user builds
    with symbolic `data` tensors; we record the callable graph lazily by just keeping
    the python closures — at run time the feed dict supplies leaf values and the
    recorded forward is executed under jax.jit.
    """

    def __init__(self):
        self._build_fns = []  # ordered (callable, feed_names, fetch_holder)
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_m, old_s = _default_main[0], _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
    try:
        yield
    finally:
        _default_main[0], _default_startup[0] = old_m, old_s


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data parity: returns a named placeholder Tensor (zeros)."""
    shape = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(jnp.zeros(shape, dtype=dtype_mod.convert_dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    t._is_placeholder = True  # type: ignore[attr-defined]
    return t


class Executor:
    """fluid/executor.py:475 Executor parity, jax.jit-backed."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        # static programs in this framework are callables recorded via
        # paddle.static.nn or user closures; the common path is Model-based.
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
            outs = out if isinstance(out, (list, tuple)) else [out]
        elif fetch_list:
            outs = fetch_list
        else:
            outs = []
        res = []
        for o in outs:
            if isinstance(o, Tensor):
                res.append(np.asarray(o._data) if return_numpy else o)
            else:
                res.append(o)
        return res


# re-exports for API-surface parity
from ..nn import ParamAttr  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from .io import load_inference_model, save_inference_model  # noqa: E402,F401
