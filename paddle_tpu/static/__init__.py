"""paddle.static parity (python/paddle/static/__init__.py).

Reference parity: the Program/Executor static-graph world — Program/Block/
Operator/Variable graph construction (fluid/framework.py:4174 Program,
:978 Block/append_op) and Executor.run(feed, fetch_list)
(fluid/executor.py:916). There, every fluid API call appends OpDescs to the
default program; Executor interprets the graph against a Scope.

TPU-native design: ops still EXECUTE eagerly at build time (placeholders are
zero arrays, so shapes are concrete), but while static mode is on every
dispatched op is also RECORDED into the default Program as
(pure_jnp_fn, arg_specs, out_ids). Executor.run slices the recorded op list
to what the fetch_list needs, replays it as one pure function of
(params, feed) and jax.jit-compiles that per feed-signature — the ParallelExecutor/
interpreter world collapses into XLA compilation. `minimize` attaches the
optimizer functionally (jax.value_and_grad over the replay + functional_apply),
the append_backward program-surgery equivalent.
"""
import contextlib
import time

import numpy as np
import jax
import jax.numpy as jnp

from .. import flags as _flags
from .. import monitor as _monitor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from ..trace import costs as _costs
from .. import trace as _trace
from ..core import dtype as dtype_mod
from ..core import dispatch as _dispatch
from ..core.tensor import Tensor, ParamBase
from ..framework import aot as _aot
from ..jit import InputSpec  # noqa: F401
from ..profiler import RecordEvent as _RecordEvent
from ..testing import failpoints as _failpoints

_STATIC_MODE = [False]

# compile_total/compile_cache_total are declared (and recorded) by
# framework/aot.py's record_compile — one mapping for every site; this
# module reports under site="executor" with the feed-signature label
_COMPILE_MS = _monitor.histogram(
    "compile_ms", "wall time to obtain an executable (fresh compile, or "
    "lower+deserialize on an AOT-cache hit)", labelnames=("site",))
_STEP_MS = _monitor.histogram(
    "step_latency_ms",
    "Executor.run / train_step wall time (host dispatch; device-complete "
    "when FLAGS_benchmark=1 forces a sync)", labelnames=("site",))
_BENCH_SYNC = _monitor.counter(
    "benchmark_sync_total",
    "FLAGS_benchmark block_until_ready syncs on fetches",
    labelnames=("site",))


def _feed_sig_label(sig):
    """Compact feed-signature label, e.g. 'x:float32[2,8]|y:int32[2]'.
    Cardinality is capped by the registry's overflow series."""
    if not sig:
        return "-"
    return "|".join(
        f"{k}:{dt}[{','.join(str(d) for d in shape)}]"
        for k, shape, dt in sig)


def _record_compile(sig, source):
    """Executor compile-cache telemetry — the shared aot.record_compile
    mapping under site=executor with the feed-signature label."""
    _aot.record_compile("executor", _feed_sig_label(sig), source)


def enable_static():
    _STATIC_MODE[0] = True


def disable_static():
    _STATIC_MODE[0] = False


def in_static_mode():
    return _STATIC_MODE[0]


def in_dynamic_mode():
    return not _STATIC_MODE[0]


class _OpRecord:
    __slots__ = ("fn", "arg_specs", "kwargs", "out_ids")

    def __init__(self, fn, arg_specs, kwargs, out_ids):
        self.fn = fn
        self.arg_specs = arg_specs  # [("var", id) | ("const", value)]
        self.kwargs = kwargs
        self.out_ids = out_ids


class Program:
    """Recorded op-list program (fluid Program/Block collapse).

    vars holds strong refs to every Tensor the graph touches; params are the
    persistable leaves (scope state), placeholders the feed slots. `_scope`
    is shared with clones — the Scope of the reference's executor."""

    def __init__(self):
        self.ops = []
        self.vars = {}          # id(tensor) -> Tensor
        self._data_ids = {}     # id(tensor._data) -> var id: functionals often
                                # RE-WRAP args (Tensor(x) shares x._data, new
                                # object id); resolving through the underlying
                                # immutable jax array keeps the var chain intact
                                # instead of baking the build-time value
        self.placeholders = {}  # feed name -> var id
        self.placeholder_shapes = {}  # feed name -> declared shape (None dims)
        self.params = {}        # var id -> param name
        self.param_names = {}   # param name -> var id
        self._initial = {}      # param name -> np.ndarray (startup values)
        self._scope = {"params": None, "opt_state": None}
        self._exec_cache = {}
        self._optimizer = None
        self._loss_id = None
        self._train_param_names = None  # None = all params the loss reaches
        self._paired_main = None        # set on startup programs by program_guard
        self._version = 0
        self.random_seed = None

    # -- building --------------------------------------------------------------
    def _register_placeholder(self, name, t, declared_shape):
        self.vars[id(t)] = t
        self._data_ids[id(t._data)] = id(t)
        self.placeholders[name] = id(t)
        self.placeholder_shapes[name] = tuple(declared_shape)

    def _register_param(self, t):
        name = t.name or f"param_{len(self.param_names)}"
        if name in self.param_names and self.param_names[name] != id(t):
            name = f"{name}_{len(self.param_names)}"
        self.vars[id(t)] = t
        self._data_ids[id(t._data)] = id(t)
        self.params[id(t)] = name
        self.param_names[name] = id(t)
        self._initial[name] = np.asarray(t._data)
        return name

    def _resolve_var(self, t):
        """SSA resolution of a Tensor to its var id. _data identity is checked
        FIRST: functionals re-wrap tensors (new object, same array) and
        apply_inplace rebinds a target's _data to the op output — in both
        cases the underlying immutable array names the current value, while
        the object id may point at a stale binding."""
        vid = self._data_ids.get(id(t._data))
        if vid is not None:
            return vid
        return id(t) if id(t) in self.vars else None

    def _record(self, fn, args, kwargs, outs):
        specs = []
        for a in args:
            if isinstance(a, Tensor):
                vid = self._resolve_var(a)
                if vid is None:
                    if isinstance(a, ParamBase) or a.persistable:
                        self._register_param(a)
                        vid = id(a)
                    else:
                        # a tensor created eagerly outside the graph: bake it
                        specs.append(("const", a._data))
                        continue
                specs.append(("var", vid))
            else:
                specs.append(("const", a))
        kw = {k: (v._data if isinstance(v, Tensor) else v)
              for k, v in kwargs.items()}
        for o in outs:
            self.vars[id(o)] = o
            self._data_ids[id(o._data)] = id(o)
        self.ops.append(_OpRecord(fn, specs, kw, [id(o) for o in outs]))
        self._version += 1

    def _rebind(self, old, new_t):
        """apply_inplace rebound new_t._data to old's value: keep a strong
        ref to new_t; _resolve_var already routes future uses through the
        shared array to `old`'s record (SSA — the old producer op stays the
        sole producer of its id)."""
        if id(old) in self.vars:
            self.vars[id(new_t)] = new_t

    # -- optimizer attachment (append_backward + optimize-op insertion) --------
    def set_optimizer(self, optimizer, loss, parameters=None,
                      no_grad_set=None):
        lid = self._resolve_var(loss) if isinstance(loss, Tensor) else None
        if lid is None:
            raise ValueError(
                "minimize(loss): loss was not built in this program "
                "(build it from static.data placeholders under program_guard)")
        self._optimizer = optimizer
        self._loss_id = lid
        self._train_param_names = None
        if parameters:
            names = set()
            for p in parameters:
                pid = self._resolve_var(p) if isinstance(p, Tensor) else None
                if pid in self.params:
                    names.add(self.params[pid])
                elif isinstance(p, str) and p in self.param_names:
                    names.add(p)
            self._train_param_names = names
        if no_grad_set:
            frozen = set()
            for p in no_grad_set:
                pid = self._resolve_var(p) if isinstance(p, Tensor) else None
                if pid in self.params:
                    frozen.add(self.params[pid])
                elif isinstance(p, str):
                    frozen.add(p)
            base = (self._train_param_names
                    if self._train_param_names is not None
                    else set(self.param_names))
            self._train_param_names = base - frozen
        self._version += 1

    # -- scope/state -----------------------------------------------------------
    def _ensure_scope(self):
        if self._scope["params"] is None:
            self._scope["params"] = {}
        # top-up: params registered since the last run initialize lazily
        for name in self.param_names:
            if name not in self._scope["params"]:
                self._scope["params"][name] = jnp.asarray(self._initial[name])

    def _reset_scope(self):
        self._scope["params"] = {
            name: jnp.asarray(self._initial[name]) for name in self.param_names
        }
        self._scope["opt_state"] = None

    def _sync_params_to_tensors(self):
        for vid, name in self.params.items():
            t = self.vars.get(vid)
            if t is not None and self._scope["params"] is not None:
                t._data = self._scope["params"][name]

    def state_dict(self):
        self._ensure_scope()
        return {n: Tensor(v) for n, v in self._scope["params"].items()}

    # -- reference API surface -------------------------------------------------
    def global_block(self):
        return self

    def all_parameters(self):
        return [self.vars[vid] for vid in self.params]

    def list_vars(self):
        return list(self.vars.values())

    def clone(self, for_test=False):
        """Shares ops/vars/scope (the reference clones the graph but runs in
        the same Scope); for_test drops the optimizer so Executor.run does
        pure inference — the canonical `test_program = main.clone(True)`."""
        c = Program.__new__(Program)
        c.__dict__ = dict(self.__dict__)
        if for_test:
            c._optimizer = None
            c._loss_id = None
        return c

    def analysis_jaxpr(self, feed=None, fetch_list=None):
        """Trace the recorded program — exactly as Executor.run would
        replay it — to a jax ClosedJaxpr for paddle_tpu.analysis.

        This is the Program-level hook for the pass registry (the
        reference's REGISTER_PASS layer inspects the Program graph; here
        the passes inspect the jaxpr of its jitted replay). The pure
        replay fn is the SAME one Executor._compile jits, so findings
        refer to the graph that actually runs. Nothing is compiled or
        executed — tracing only.

            prog.analysis_jaxpr(feed={"x": np.zeros((4, 8), "float32")})

        fetch_list defaults to the outputs of the last recorded op (or
        the attached loss when an optimizer is set). A program with an
        optimizer attached traces the TRAIN step (forward + grads +
        optimizer update), matching what Executor.run executes for it.
        """
        feed = {k: jnp.asarray(np.asarray(v))
                for k, v in (feed or {}).items()}
        self._ensure_scope()
        exe = Executor()
        if fetch_list:
            fetch_ids = tuple(exe._fetch_id(self, f) for f in fetch_list)
        elif self._loss_id is not None:
            fetch_ids = (self._loss_id,)
        elif self.ops:
            fetch_ids = tuple(self.ops[-1].out_ids)
        else:
            raise ValueError("analysis_jaxpr: empty program (no recorded "
                             "ops) and no fetch_list")
        train = self._optimizer is not None and self._loss_id is not None
        fn = _build_program_fn(self, tuple(feed), fetch_ids, train=train)
        params = self._scope["params"]
        if not train:
            return jax.make_jaxpr(fn)(params, feed)
        opt = self._optimizer
        opt_state = (self._scope["opt_state"]
                     if self._scope["opt_state"] is not None
                     else opt.functional_init(params))
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        return jax.make_jaxpr(fn)(params, opt_state, lr, feed)

    def aot_compile(self, feed_specs, fetch_list=None):
        """Warm-start: compile the EXACT executable Executor.run would jit
        for this feed signature — from shape specs, no real batch — and
        park it in the program's jit cache (plus the on-disk AOT cache
        when FLAGS_jit_cache_dir is set).

            prog.aot_compile({"x": ((8, 13), "float32"),
                              "y": ((8, 1), "float32")},
                             fetch_list=[loss])

        feed_specs: {name: (shape, dtype) | InputSpec | ShapeDtypeStruct}.
        fetch_list defaults to the attached loss (train programs) or the
        last recorded op's outputs — pass the same fetch_list the serving
        run will use, since the cache key includes the fetch set. A
        program with an optimizer attached compiles the TRAIN step.
        Works without the disk flag too (in-memory AOT). Returns where
        the executable came from: "memory"|"disk"|"fresh"."""
        feed = {}
        for name in sorted(feed_specs):
            spec = feed_specs[name]
            if isinstance(spec, jax.ShapeDtypeStruct):
                shape, dtype = spec.shape, spec.dtype
            elif isinstance(spec, InputSpec):
                shape, dtype = spec.shape, spec.dtype
            else:
                shape, dtype = spec
            feed[name] = jax.ShapeDtypeStruct(
                tuple(shape), dtype_mod.convert_dtype(dtype))
        self._ensure_scope()
        exe = Executor()
        if fetch_list:
            fetch_ids = tuple(exe._fetch_id(self, f) for f in fetch_list)
        elif self._loss_id is not None:
            fetch_ids = (self._loss_id,)
        elif self.ops:
            fetch_ids = tuple(self.ops[-1].out_ids)
        else:
            raise ValueError("aot_compile: empty program (no recorded ops) "
                             "and no fetch_list")
        train, sig, key, lr, example = _exec_key_and_example(
            self, feed, fetch_ids)
        if key in self._exec_cache:
            _record_compile(sig, "memory")  # warm audits count this too
            return "memory"
        with _RecordEvent("executor/compile"), \
                _monitor.timed(_COMPILE_MS.labels(site="executor")):
            compiled, source = exe._compile(self, tuple(feed), fetch_ids,
                                            train, example, force=True)
        self._exec_cache[key] = compiled
        _record_compile(sig, source)
        _costs.record("executor", _feed_sig_label(sig),
                            _aot.executable_of(compiled))
        return source


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_m, old_s = _default_main[0], _default_startup[0]
    _default_main[0] = main_program
    if startup_program is not None:
        _default_startup[0] = startup_program
        # running the startup later must initialize THIS main program's
        # params, wherever the defaults point at that moment
        startup_program._paired_main = main_program
    try:
        yield
    finally:
        _default_main[0], _default_startup[0] = old_m, old_s


def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data parity: a named feed placeholder.

    Build-time value is zeros with None dims -> 1, so downstream ops execute
    (and shape-infer) concretely; Executor.run replaces it with the fed batch."""
    declared = list(shape)
    concrete = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(jnp.zeros(concrete, dtype=dtype_mod.convert_dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    if _STATIC_MODE[0]:
        _default_main[0]._register_placeholder(name, t, declared)
    return t


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """fluid.backward.append_backward parity: in this design gradients are
    derived by jax.value_and_grad over the recorded replay at run time, so
    this only validates that `loss` belongs to the default program."""
    prog = _default_main[0]
    if id(loss) not in prog.vars:
        raise ValueError("append_backward: loss is not a var of the default "
                         "main program")
    return []


# -- the dispatch hooks --------------------------------------------------------

from ..core.tape import global_tape as _global_tape  # noqa: E402


def _record_hook(fn, args, kwargs, outs):
    if not _STATIC_MODE[0]:
        return
    # tape paused == inside a jitted trainer/StaticFunction trace: those
    # compile their own programs; recording their tracer ops would leak
    if not _global_tape().enabled:
        return
    _default_main[0]._record(fn, args, kwargs, outs)


def _rebind_hook(old, new_t):
    if not _STATIC_MODE[0]:
        return
    _default_main[0]._rebind(old, new_t)


_dispatch._STATIC_RECORDER[0] = _record_hook
_dispatch._STATIC_REBIND[0] = _rebind_hook


# -- execution -----------------------------------------------------------------

def _exec_key_and_example(program, feed, fetch_ids):
    """The ONE source of the executor's jit-cache key and AOT example
    args, shared by Executor._run_program and Program.aot_compile so a
    warm-started entry is exactly the one run() later looks up. `feed`
    maps name -> array or ShapeDtypeStruct in canonical (sorted) order;
    materializes optimizer state (train programs) as a side effect.
    Returns (train, sig, key, lr, example_args)."""
    train = program._optimizer is not None and program._loss_id is not None
    sig = tuple((k, v.shape, str(v.dtype)) for k, v in feed.items())
    key = (program._version, train, fetch_ids, sig)
    scope = program._scope
    lr = None
    if train:
        # optimizer state materializes BEFORE compile: the AOT path
        # lowers against the live (params, opt_state, lr, feed) values
        opt = program._optimizer
        if scope["opt_state"] is None:
            scope["opt_state"] = opt.functional_init(scope["params"])
        else:
            for n, v in scope["params"].items():
                if n not in scope["opt_state"]:
                    scope["opt_state"][n] = opt.functional_init({n: v})[n]
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        example = (scope["params"], scope["opt_state"], lr, feed)
    else:
        example = (scope["params"], feed)
    return train, sig, key, lr, example


def _slice_ops(program, target_ids):
    """Backward slice: only ops the targets (+loss) actually need run."""
    producer = {}
    for idx, op in enumerate(program.ops):
        for oid in op.out_ids:
            producer[oid] = idx
    needed = set()
    stack = [t for t in target_ids if t is not None]
    while stack:
        vid = stack.pop()
        idx = producer.get(vid)
        if idx is None or idx in needed:
            continue
        needed.add(idx)
        for spec in program.ops[idx].arg_specs:
            if spec[0] == "var":
                stack.append(spec[1])
    return [program.ops[i] for i in sorted(needed)]


class Executor:
    """fluid/executor.py:916 Executor parity: run(feed, fetch_list) over the
    recorded program, jax.jit-compiled per (program version, feed signature,
    fetch set). Running an empty program (the startup program) initializes
    the default main program's parameters — the startup-initializer-ops run."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True,
            scope=None):
        feed = feed or {}
        if program is None:
            program = default_main_program()
        if callable(program) and not isinstance(program, Program):
            # legacy path: a plain python callable "program"
            out = program(**feed)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [np.asarray(o._data) if isinstance(o, Tensor) and return_numpy
                    else o for o in outs]
        if not isinstance(program, Program):
            return []
        if not program.ops:
            # startup program: (re)run parameter initialization for the main
            # program it was paired with (fallback: the current default)
            main = program._paired_main or default_main_program()
            main._reset_scope()
            return []
        return self._run_program(program, feed, fetch_list or [], return_numpy)

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """fluid/executor.py train_from_dataset parity: drive the recorded
        program from an InMemoryDataset/QueueDataset — slot names feed the
        matching static.data placeholders batch by batch (the reference's
        hogwild_worker.cc:195-211 DataFeed->Program loop).

        Ragged slots pad per batch; a new pad width jit-compiles a new feed
        signature (fixed-length slots compile exactly once)."""
        if dataset is None:
            raise ValueError("train_from_dataset requires dataset=")
        program = program or default_main_program()
        names = set(program.placeholders) if isinstance(program, Program) \
            else None
        last = None
        for step, batch in enumerate(dataset.batch_iter()):
            feed = {k: v for k, v in batch.items()
                    if names is None or k in names}
            last = self.run(program, feed=feed, fetch_list=fetch_list)
            if debug and fetch_list and step % max(1, print_period) == 0:
                info = fetch_info or [f"fetch{i}"
                                      for i in range(len(fetch_list))]
                vals = ", ".join(f"{n}={np.asarray(v).mean():.6f}"
                                 for n, v in zip(info, last))
                print(f"[train_from_dataset] step {step}: {vals}")
        return last

    def infer_from_dataset(self, program=None, dataset=None, **kwargs):
        """Inference twin: NEVER runs the optimizer — a program that has one
        attached is evaluated through its for_test clone (is_infer=True
        semantics; the reference skips gradient push on this path)."""
        program = program or default_main_program()
        if isinstance(program, Program) and program._optimizer is not None:
            program = program.clone(for_test=True)
        return self.train_from_dataset(program=program, dataset=dataset,
                                       **kwargs)

    # -- internals -------------------------------------------------------------
    def _fetch_id(self, program, f):
        if isinstance(f, Tensor):
            vid = program._resolve_var(f)  # handles re-wraps and in-place
            if vid is not None:
                return vid
            raise ValueError(f"fetch var {getattr(f, 'name', f)} is not part "
                             "of the program")
        if isinstance(f, str):
            if f in program.placeholders:
                return program.placeholders[f]
            if f in program.param_names:
                return program.param_names[f]
            for t in program.vars.values():
                if getattr(t, "name", None) == f:
                    return id(t)
            raise ValueError(f"fetch name '{f}' not found in program")
        raise TypeError(f"cannot fetch {type(f).__name__}")

    def _run_program(self, program, feed, fetch_list, return_numpy):
        # window beacon: watched only while a run (compile included) is
        # actually in flight — a finished session never reads as a stall
        with _blackbox.progress("executor/run"):
            return self._run_program_impl(program, feed, fetch_list,
                                          return_numpy)

    def _run_program_impl(self, program, feed, fetch_list, return_numpy):
        t_step = time.perf_counter()
        program._ensure_scope()
        fetch_ids = tuple(self._fetch_id(program, f) for f in fetch_list)
        # canonical (sorted) feed order: the jit-cache key sorts the
        # signature, so the compiled closure must be built from the same
        # order — otherwise two insertion orders of the same feed dict
        # alias one cache entry built from whichever order arrived first
        feed_arrays = {k: jnp.asarray(np.asarray(feed[k]))
                       for k in sorted(feed)}
        train, sig, key, lr, example = _exec_key_and_example(
            program, feed_arrays, fetch_ids)
        # cache lives ON the program (not the executor) so dropped programs
        # release their compiled closures and baked arrays with them
        cache = program._exec_cache
        scope = program._scope
        sig_label = _feed_sig_label(sig)   # computed ONCE per run
        if key not in cache:
            with _RecordEvent("executor/compile"), \
                    _monitor.timed(_COMPILE_MS.labels(site="executor")):
                # FLAGS_trace forces an eager AOT compile (in memory) so
                # the cost registry can read the executable's
                # cost_analysis(); flag unset keeps the lazy-jit bypass
                cache[key], source = self._compile(
                    program, tuple(feed_arrays), fetch_ids, train, example,
                    force=_trace.is_enabled())
            _aot.record_compile("executor", sig_label, source)
            _costs.record("executor", sig_label,
                          _aot.executable_of(cache[key]))
        else:
            source = "memory"
            _aot.record_compile("executor", sig_label, "memory")
        compiled = cache[key]
        # step span: compile-cache source + feed signature + sync time —
        # the executor half of the ISSUE-5 end-to-end trace propagation
        sp = _trace.span("executor/run", subsystem="executor",
                         sig=sig_label, source=source, train=train)
        with sp, _RecordEvent("executor/run"):
            if train:
                opt = program._optimizer
                new_p, new_s, fetches = compiled(scope["params"],
                                                 scope["opt_state"], lr,
                                                 feed_arrays)
                scope["params"] = new_p
                scope["opt_state"] = new_s
                opt._step_count += 1
                program._sync_params_to_tensors()
            else:
                fetches = compiled(scope["params"], feed_arrays)
            if _flags.get_flag("benchmark"):
                # step timings measure DEVICE work, not dispatch: block on
                # every fetch (train steps also pin the updated params so
                # a fetchless run(feed=...) still syncs the real step)
                t_sync = time.perf_counter()
                sync_on = list(fetches)
                if train and scope["params"]:
                    sync_on.append(next(iter(scope["params"].values())))
                for f in sync_on:
                    if hasattr(f, "block_until_ready"):
                        f.block_until_ready()
                _BENCH_SYNC.labels(site="executor").inc()
                sp.set(sync_ms=(time.perf_counter() - t_sync) * 1e3)
        if _monitor.is_enabled():
            _STEP_MS.labels(site="executor").observe(
                (time.perf_counter() - t_step) * 1e3)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def _compile(self, program, feed_names, fetch_ids, train, example_args,
                 force=False):
        """jit the pure replay; with FLAGS_jit_cache_dir set, compile it
        eagerly through the persistent executable cache (framework/aot.py).
        Returns (callable, source: bypass|disk|fresh); `example_args` may
        mix live arrays and jax.ShapeDtypeStructs. force=True (aot_compile)
        compiles eagerly even without a cache dir — warm-start must never
        hand back a lazy jit."""
        _failpoints.failpoint("exe/compile")
        jitted = jax.jit(_build_program_fn(program, feed_names, fetch_ids,
                                           train))
        return _aot.compile_cached(jitted, example_args, site="executor",
                                   extra_key=("executor", train),
                                   force=force)


def _build_program_fn(program, feed_names, fetch_ids, train):
    """Build the pure replay fn Executor jits: (params, feed) -> fetches
    for eval, (params, opt_state, lr, feed) -> (params', state', fetches)
    for train. Shared with Program.analysis_jaxpr so the analysis passes
    see the exact graph the executor runs."""
    targets = list(fetch_ids) + ([program._loss_id] if train else [])
    ops = _slice_ops(program, targets)

    # validate feeds BEFORE jit: every needed placeholder must be fed
    bound = set()
    for name in feed_names:
        if name not in program.placeholders:
            raise ValueError(f"feed '{name}' is not a static.data "
                             "placeholder of this program")
        bound.add(program.placeholders[name])
    bound |= set(program.params)
    def _missing(vid, what):
        for n, pvid in program.placeholders.items():
            if pvid == vid:
                raise ValueError(f"placeholder '{n}' is required by the "
                                 f"{what} but missing from feed")
        raise ValueError(f"{what} references a var with no producer "
                         "(was it built in a different program?)")

    for op in ops:
        for spec in op.arg_specs:
            if spec[0] == "var" and spec[1] not in bound:
                _missing(spec[1], "fetch_list")
        bound |= set(op.out_ids)
    for fid in targets:
        if fid is not None and fid not in bound:
            _missing(fid, "fetch_list")

    ph = program.placeholders
    params_map = dict(program.params)

    def forward(param_arrays, feed_arrays):
        env = {}
        for name, arr in feed_arrays.items():
            env[ph[name]] = arr
        for vid, name in params_map.items():
            env[vid] = param_arrays[name]
        for op in ops:
            vals = [env[s[1]] if s[0] == "var" else s[1]
                    for s in op.arg_specs]
            out = op.fn(*vals, **op.kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for oid, o in zip(op.out_ids, outs):
                env[oid] = o
        return env

    if not train:
        def ev(param_arrays, feed_arrays):
            env = forward(param_arrays, feed_arrays)
            return [env[i] for i in fetch_ids]

        return ev

    opt = program._optimizer
    loss_id = program._loss_id  # snapshot: closures must not pin program
    # update ONLY params the sliced loss graph actually uses (a second
    # model in the same program must not weight-decay toward zero), and
    # honor minimize(parameters=/no_grad_set=)
    used = set()
    for op in ops:
        for s in op.arg_specs:
            if s[0] == "var" and s[1] in params_map:
                used.add(params_map[s[1]])
    train_names = (used if program._train_param_names is None
                   else used & program._train_param_names)

    def step(param_arrays, opt_state, lr, feed_arrays):
        sub = {n: param_arrays[n] for n in train_names}

        def loss_fn(sp):
            env = forward({**param_arrays, **sp}, feed_arrays)
            return env[loss_id].astype(jnp.float32), env

        (_, env), grads = jax.value_and_grad(loss_fn, has_aux=True)(sub)
        sub_state = {n: opt_state[n] for n in train_names}
        sub_state["__step__"] = opt_state["__step__"]
        new_sub, new_sub_state = opt.functional_apply(sub, grads,
                                                      sub_state, lr=lr)
        new_p = {**param_arrays, **new_sub}
        new_s = {**opt_state, **new_sub_state}
        return new_p, new_s, [env[i] for i in fetch_ids]

    return step


# re-exports for API-surface parity
from ..nn import ParamAttr  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from .io import load_inference_model, save_inference_model  # noqa: E402,F401


# --------------------------------------------------------------------------
# fluid compat surface (python/paddle/static/__init__.py parity): scope /
# places / program-state helpers. Scopes collapse onto the Program's param
# store; places map to jax devices.
# --------------------------------------------------------------------------

Variable = object  # recorded vars are plain Tensors; kept for isinstance-free code


class _GlobalScope:
    def find_var(self, name):
        prog = default_main_program()
        t = prog._params_by_name.get(name) if hasattr(prog, "_params_by_name") else None

        class _Var:
            def __init__(self, t):
                self._t = t

            def get_tensor(self):
                return self._t

        return _Var(t) if t is not None else None


_global_scope = _GlobalScope()


def global_scope():
    return _global_scope


class scope_guard:
    """Compat context manager: scopes are implicit (one per Program)."""

    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *a):
        return False


import contextlib as _contextlib


@_contextlib.contextmanager
def name_scope(prefix=None):
    yield


def cpu_places(device_count=None):
    import jax

    devs = [d for d in jax.devices() if d.platform == "cpu"] or jax.devices()
    return devs[: device_count or len(devs)]


def cuda_places(device_ids=None):
    import jax

    return list(jax.devices())


def xpu_places(device_ids=None):
    import jax

    return list(jax.devices())


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k, correct=correct, total=total)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    import numpy as np

    from ..core.tensor import Tensor
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.float32(m.accumulate())))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as _grad

    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)
    return outs


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """py_func_op.cc parity: host-python op on tensor values. With
    `backward_func`, gradients flow: it is attached as the op's VJP and
    receives (*inputs, *outputs, *output_grads) host arrays, returning the
    input grads (the reference's backward py_func contract). Without it the
    outputs are detached — same as the reference, whose py_func has no grad
    op unless backward_func is given."""
    import numpy as np

    import jax
    from ..core.dispatch import apply
    from ..core.tensor import Tensor
    import jax.numpy as jnp

    xs = x if isinstance(x, (list, tuple)) else [x]
    ts = [v if isinstance(v, Tensor) else Tensor(jnp.asarray(np.asarray(v)))
          for v in xs]

    if backward_func is None:
        host = [np.asarray(v._data) for v in ts]
        res = func(*host)
        if not isinstance(res, (list, tuple)):
            res = [res]
        outs = [Tensor(jnp.asarray(np.asarray(r))) for r in res]
        for o in outs:
            o.stop_gradient = True
        return outs if len(outs) > 1 else outs[0]

    multi = [None]  # whether func returned a tuple (fixed at first call)

    @jax.custom_vjp
    def _op(*arrs):
        res = func(*[np.asarray(a) for a in arrs])
        multi[0] = isinstance(res, (list, tuple))
        res = res if multi[0] else [res]
        out = tuple(jnp.asarray(np.asarray(r)) for r in res)
        return out if len(out) > 1 else out[0]

    def _fwd(*arrs):
        out = _op(*arrs)
        return out, (arrs, out if isinstance(out, tuple) else (out,))

    def _bwd(resid, gout):
        arrs, outs_v = resid
        gs = gout if isinstance(gout, tuple) else (gout,)
        host = ([np.asarray(a) for a in arrs]
                + [np.asarray(o) for o in outs_v]
                + [np.asarray(g) for g in gs])
        gx = backward_func(*host)
        if not isinstance(gx, (list, tuple)):
            gx = [gx]
        return tuple(jnp.asarray(np.asarray(g)) for g in gx)

    _op.defvjp(_fwd, _bwd)
    result = apply(_op, *ts)
    return result


def save(program, model_path, protocol=4):
    import pickle

    state = {k: v for k, v in (program.state_dict() or {}).items()}
    import numpy as np

    with open(model_path + ".pdparams" if not model_path.endswith(".pdparams")
              else model_path, "wb") as f:
        pickle.dump({k: np.asarray(t._data) for k, t in state.items()}, f,
                    protocol=protocol)


def _write_program_params(program, arrs):
    """Write named arrays into the Program's parameter scope (state_dict()
    hands out copies, so mutating those would be a silent no-op)."""
    import jax.numpy as jnp

    program._ensure_scope()
    store = program._scope["params"]
    for k, v in arrs.items():
        if k in store:
            store[k] = jnp.asarray(v)
    program._sync_params_to_tensors()


def load(program, model_path, executor=None, var_list=None):
    import pickle

    path = model_path if model_path.endswith(".pdparams") else model_path + ".pdparams"
    with open(path, "rb") as f:
        arrs = pickle.load(f)
    _write_program_params(program, arrs)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    save(main_program or default_main_program(),
         __import__("os").path.join(dirname, filename or "params"))


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    load(main_program or default_main_program(),
         __import__("os").path.join(dirname, filename or "params"))


def load_program_state(model_path, var_list=None):
    import pickle

    path = model_path if model_path.endswith(".pdparams") else model_path + ".pdparams"
    with open(path, "rb") as f:
        return pickle.load(f)


def set_program_state(program, state):
    _write_program_params(program, state)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """print_op.cc parity: prints the tensor when the program runs (eager:
    immediately; traced: via jax.debug.print) and passes it through."""
    from ..core.tensor import Tensor
    from ..core.dispatch import apply
    import jax

    def fn(v):
        jax.debug.print((message or "") + "{}", v)
        return v

    return apply(fn, input if isinstance(input, Tensor) else Tensor(input))


def Assert(cond, data=None, summarize=20, name=None):
    """assert_op.cc parity (fluid.layers.Assert): halt with the tensor data
    when `cond` is false. Traced predicates check host-side via debug
    callback (the reference op prints `data` then throws); concrete ones
    raise immediately."""
    from ..jit.dy2static import convert_assert

    items = list(data) if isinstance(data, (list, tuple)) else (
        [data] if data is not None else [])

    def msg():
        shown = []
        for d in items:
            v = d._data if isinstance(d, Tensor) else d
            try:
                shown.append(str(np.asarray(v).reshape(-1)[:summarize]))
            except Exception:  # still-traced aux data: name it, don't crash
                shown.append(f"<traced {getattr(v, 'shape', '?')}>")
        return "Assert failed: " + "; ".join(shown) if shown else \
            "Assert failed"

    convert_assert(cond, msg)


class BuildStrategy:
    """Compat knobs (reference pass toggles). XLA owns fusion/layout here;
    attributes are accepted and ignored."""

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class ExecutionStrategy:
    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class CompiledProgram:
    """Compat wrapper: Executor.run already jits the recorded Program, so
    with_data_parallel is a no-op that remembers its Program."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None):
        self._program = main_program or default_main_program()

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        exe = Executor()
        return exe.run(self._program, feed=feed, fetch_list=fetch_list,
                       return_numpy=return_numpy)


class WeightNormParamAttr:
    """Compat: weight-norm reparameterization is applied via
    paddle.nn.utils.weight_norm on layers; this records the intent."""

    def __init__(self, dim=None, name=None, **kwargs):
        self.dim = dim
        self.name = name
        self.kwargs = kwargs
