"""Op dispatch: the Tracer::TraceOp equivalent.

Reference parity: paddle/fluid/imperative/tracer.cc:132 (TraceOp — runs the kernel, then
CreateGradOpNode layer.cc:445 if any input requires grad) and the generated
core.ops.<op> fast path (pybind/op_function_generator.cc:490).

TPU-native design: one generic `apply(fn, *args, **kwargs)` replaces 494 generated
bindings. `fn` is a pure jnp function; differentiable Tensor args are functionalized and
run through `jax.vjp` so the pullback (XLA-derived grad) lands on the tape. Non-floating
inputs and stop_gradient inputs are closed over as constants.
"""
import jax

from . import dtype as dtype_mod
from .tape import Node, global_tape
from .tensor import Tensor


def _needs_grad(t):
    return (not t.stop_gradient) and dtype_mod.is_floating(t.dtype)


# Static-graph op recorder (paddle_tpu.static installs itself here): when
# static mode is on, every dispatched op is appended to the default Program
# so Executor.run can replay it — the TraceOp -> OpDesc path of the
# reference's static world (fluid/framework.py append_op).
_STATIC_RECORDER = [None]
_STATIC_REBIND = [None]


def apply(fn, *args, n_outputs=None, **kwargs):
    """Run `fn` over the raw values of Tensor args; tape a vjp node if needed.

    Only Tensor positional args participate in autodiff. Returns Tensor or tuple of
    Tensors mirroring fn's output structure (tuple/list -> tuple).
    """
    tape = global_tape()
    diff_idx = []
    diff_tensors = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor) and _needs_grad(a):
            diff_idx.append(i)
            diff_tensors.append(a)

    record = tape.enabled and bool(diff_tensors)

    # template holds RAW values only (no Tensor objects): the Node retains
    # `pure` for create_graph, so closing over Tensors would pin their
    # grads/hooks/node graph for the tape's lifetime
    template = [a._data if isinstance(a, Tensor) else a for a in args]

    def pure(*vals):
        call = list(template)
        for j, i in enumerate(diff_idx):
            call[i] = vals[j]
        return fn(*call, **kwargs)

    saved_in = [t._data for t in diff_tensors]
    if record:
        out, vjp_fn = jax.vjp(pure, *saved_in)
    else:
        out = pure(*saved_in)

    multi = isinstance(out, (tuple, list))
    raw_outs = list(out) if multi else [out]
    out_tensors = []
    for o in raw_outs:
        t = Tensor.__new__(Tensor)
        t._data = o
        t.stop_gradient = not record
        t.grad = None
        t._node = None
        t.name = ""
        t.persistable = False
        t.retain_grads = False
        t._hooks = None
        out_tensors.append(t)

    if record:
        def pullback(cot_list, _vjp=vjp_fn, _multi=multi):
            return _vjp(tuple(cot_list) if _multi else cot_list[0])

        node = Node(diff_tensors, out_tensors, pullback, pure=pure,
                    multi=multi, saved_in=saved_in)
        for t in out_tensors:
            t._node = node
        tape.record(node)

    rec = _STATIC_RECORDER[0]
    if rec is not None:
        rec(fn, args, kwargs, out_tensors)

    if multi:
        return tuple(out_tensors)
    return out_tensors[0]


def apply_inplace(fn, target, *args, **kwargs):
    """In-place op: computes fn and rebinds target._data, keeping grad flow.

    Mirrors paddle inplace ops (e.g. add_, scale_); TensorInplaceVersion
    (framework/tensor.h:77) bumping is unnecessary — the tape holds the old value in the
    vjp residuals, so inplace rebinding is always autograd-safe here.
    """
    out = apply(fn, target, *args, **kwargs)
    target._data = out._data
    target._node = out._node
    if out._node is not None:
        # make the recorded node point at the *target* so future grads flow
        idx = out._node.outputs.index(out)
        out._node.outputs[idx] = target
        target.stop_gradient = out.stop_gradient
    reb = _STATIC_REBIND[0]
    if reb is not None:
        reb(out, target)
    return target
