"""Eager autograd tape.

Reference parity: paddle/fluid/imperative/ — Tracer::TraceOp (tracer.cc:132) records a grad
node per op; BasicEngine (basic_engine.cc:39,265) runs the queue-driven reverse walk;
GradientAccumulator (gradient_accumulator.h:27) sums multi-consumer grads.

TPU-native design: instead of per-op hand-written grad kernels, every recorded node stores
the `jax.vjp` pullback of the pure function that produced it, so backward is a reverse walk
calling pullbacks — XLA differentiates each op. The tape is global, append-only, and cleared
after `backward()` (retain_graph semantics supported). Under `no_grad()` or `pause()`
nothing is recorded, which is also how jit-traced (to_static) code avoids taping.
"""
import contextlib

import jax


class Node:
    __slots__ = ("inputs", "outputs", "pullback", "alive", "pure", "multi",
                 "saved_in")

    def __init__(self, inputs, outputs, pullback, pure=None, multi=False,
                 saved_in=None):
        self.inputs = inputs      # list[Tensor] (only differentiable tensor args)
        self.outputs = outputs    # list[Tensor]
        self.pullback = pullback  # vjp function: cotangents-tuple -> input cotangents
        self.alive = True
        # for create_graph (double grad): the pure fn over the diff inputs'
        # raw values, and those values AT RECORD TIME (detects in-place
        # rebinding — re-deriving the vjp at mutated values would be wrong)
        self.pure = pure
        self.multi = multi
        self.saved_in = saved_in


# nodes held without a backward() call before a one-time leak warning fires:
# forward-only loops over requires-grad tensors (RL rollouts, eval phases
# without no_grad) otherwise grow the tape unboundedly and silently
_LEAK_WARN_THRESHOLD = 100_000


class Tape:
    def __init__(self):
        self.nodes = []
        self._paused = 0
        self._leak_warned = False

    @property
    def enabled(self):
        return self._paused == 0

    def record(self, node):
        self.nodes.append(node)
        if (not self._leak_warned
                and len(self.nodes) >= _LEAK_WARN_THRESHOLD):
            import warnings

            self._leak_warned = True
            warnings.warn(
                f"autograd tape holds {len(self.nodes)} nodes with no "
                "backward() — a forward-only loop over tensors with "
                "stop_gradient=False leaks memory; wrap inference in "
                "paddle.no_grad() or call tensor.backward()/tape.clear()",
                ResourceWarning)

    def clear(self):
        self.nodes.clear()
        self._leak_warned = False

    @contextlib.contextmanager
    def pause(self):
        self._paused += 1
        try:
            yield
        finally:
            self._paused -= 1


_TAPE = Tape()


def global_tape():
    return _TAPE


def no_grad():
    """paddle.no_grad parity (python/paddle/fluid/dygraph/base.py no_grad)."""
    return _TAPE.pause()


def is_grad_enabled():
    return _TAPE.enabled


def _zeros_like_val(v):
    import jax.numpy as jnp

    return jnp.zeros_like(v)


def backward(loss_tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, targets=None):
    """Run reverse accumulation from `loss_tensors`.

    Mirrors BasicEngine::Execute (imperative/basic_engine.cc:265): walk recorded nodes in
    reverse creation order; a node fires if any of its outputs has a pending cotangent;
    input cotangents accumulate into `Tensor.grad` for leaves and into pending buffers for
    interior tensors.

    create_graph=True (PartialGradEngine double-grad parity): every pullback
    is re-derived from the node's pure fn and executed THROUGH the dispatcher,
    so the produced gradients are themselves taped — grad-of-grad works.

    `targets` (a set of tensor ids) restricts which LEAVES accumulate .grad
    — paddle.grad's only_inputs=True (PartialGradEngine pruning).
    """
    if create_graph:
        return _backward_create_graph(loss_tensors, grad_tensors,
                                      retain_graph, targets)
    import jax.numpy as jnp

    if not isinstance(loss_tensors, (list, tuple)):
        loss_tensors = [loss_tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(loss_tensors)
    if not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # pending cotangents keyed by id(tensor); keep tensor refs alive alongside
    pending = {}

    def add_pending(t, g):
        k = id(t)
        if k in pending:
            pending[k] = (t, pending[k][1] + g)
        else:
            pending[k] = (t, g)

    for t, g in zip(loss_tensors, grad_tensors):
        if g is None:
            gval = jnp.ones_like(t._data)
        else:
            gval = g._data if hasattr(g, "_data") else jnp.asarray(g)
        add_pending(t, gval)

    for node in reversed(_TAPE.nodes):
        if not node.alive:
            continue
        outs_g = []
        fired = False
        for o in node.outputs:
            entry = pending.get(id(o))
            if entry is not None:
                outs_g.append(entry[1])
                fired = True
            else:
                outs_g.append(_zeros_like_val(o._data))
        if not fired:
            continue
        # consume the outputs' pending cotangents — an in-place op aliases its output
        # tensor with an earlier node's output, so leaving them would double-count
        for o in node.outputs:
            pending.pop(id(o), None)
        cots = node.pullback(outs_g)  # dispatch wraps vjp_fn to take a list
        for inp, cot in zip(node.inputs, cots):
            if cot is None:
                continue
            if getattr(cot, "dtype", None) is not None and str(cot.dtype) == "float0":
                continue
            if inp.stop_gradient:
                continue
            if inp._node is None:
                # leaf: accumulate into .grad (GradientAccumulator semantics)
                if targets is None or id(inp) in targets:
                    inp._accumulate_grad(cot)
            else:
                add_pending(inp, cot)
                # also expose interior grads if user asked (retain_grads)
                if getattr(inp, "retain_grads", False):
                    inp._accumulate_grad(cot)
        if not retain_graph:
            node.alive = False

    if not retain_graph:
        _TAPE.clear()


def _backward_create_graph(loss_tensors, grad_tensors, retain_graph,
                           targets=None):
    """Taped reverse sweep: cotangents flow as Tensors through dispatch.apply,
    so second-order backward() over the produced .grad tensors works.

    Each node's vjp is re-derived from node.pure (re-runs that op's forward —
    the FLOP cost of higher-order grads). Inputs rebound by an in-place op
    since recording are detected via node.saved_in and raise (the reference's
    inplace-version check); nodes without a pure fn (PyLayer) raise too."""
    import jax
    import jax.numpy as jnp

    from .dispatch import apply as _apply
    from .tensor import Tensor

    if not isinstance(loss_tensors, (list, tuple)):
        loss_tensors = [loss_tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(loss_tensors)
    if not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    pending = {}  # id(tensor) -> (tensor, cot Tensor)

    def add_pending(t, g):
        k = id(t)
        if k in pending:
            pending[k] = (t, pending[k][1] + g)
        else:
            pending[k] = (t, g)

    for t, g in zip(loss_tensors, grad_tensors):
        if g is None:
            gt = Tensor(jnp.ones_like(t._data), stop_gradient=True)
        elif hasattr(g, "_data"):
            gt = g
        else:
            gt = Tensor(jnp.asarray(g), stop_gradient=True)
        add_pending(t, gt)

    def accumulate(inp, cot):
        """TAPED accumulation into .grad with the normal path's dtype cast
        and registered-hook semantics (both as taped ops)."""
        if cot._data.dtype != inp._data.dtype:
            cot = cot.astype(inp._data.dtype)
        if inp._hooks:
            for h in inp._hooks:
                out = h(cot)
                if out is not None:
                    cot = out
        inp.grad = cot if inp.grad is None else inp.grad + cot

    for node in reversed(_TAPE.nodes):
        if not node.alive:
            continue
        cot_tensors = []
        fired = False
        for o in node.outputs:
            entry = pending.get(id(o))
            if entry is not None:
                cot_tensors.append(entry[1])
                fired = True
            else:
                cot_tensors.append(
                    Tensor(jnp.zeros_like(o._data), stop_gradient=True))
        if not fired:
            continue
        if node.pure is None:
            raise RuntimeError(
                "backward(create_graph=True) through a PyLayer/custom node "
                "is not supported: the node records no re-derivable pure "
                "function for second-order gradients")
        if node.saved_in is not None and any(
                s is not t._data
                for s, t in zip(node.saved_in, node.inputs)):
            raise RuntimeError(
                "backward(create_graph=True): an input of a recorded op was "
                "rebound by an in-place op (or mutated) after the forward — "
                "re-deriving its vjp would be wrong. Remove the in-place op "
                "or avoid create_graph through it (inplace-version check, "
                "imperative/variable_wrapper.h parity)")
        for o in node.outputs:
            pending.pop(id(o), None)

        n_in = len(node.inputs)

        def pull(*vals, _pure=node.pure, _n=n_in, _multi=node.multi):
            ins, cots = vals[:_n], vals[_n:]
            _, vjp_fn = jax.vjp(_pure, *ins)
            return vjp_fn(tuple(cots) if _multi else cots[0])

        out = _apply(pull, *node.inputs, *cot_tensors)
        cots = list(out) if isinstance(out, tuple) else [out]
        for inp, cot in zip(node.inputs, cots):
            if cot is None or inp.stop_gradient:
                continue
            if inp._node is None:
                # leaf: .grad stays TAPED (the whole point of create_graph)
                if targets is None or id(inp) in targets:
                    accumulate(inp, cot)
            else:
                add_pending(inp, cot)
                if getattr(inp, "retain_grads", False):
                    accumulate(inp, cot)
        if not retain_graph:
            node.alive = False

    # the plain path clears the whole tape; here new (taped-grad) nodes must
    # survive for the second backward — drop only the consumed ones
    if not retain_graph:
        _TAPE.nodes = [n for n in _TAPE.nodes if n.alive]
