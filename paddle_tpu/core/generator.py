"""RNG generator: paddle global-seed facade over explicit JAX PRNG keys.

Reference parity: paddle/fluid/framework/generator.cc (per-device seeded Generator feeding
dropout/random ops); python/paddle/framework/random.py (paddle.seed).
TPU-native design: a Generator owns a jax PRNG key; every draw splits the key. Under a jit
trace, drawing from the *global* generator would bake a constant key into the compiled
program, so traced code paths (to_static / Model.fit static mode) must thread keys
explicitly — `fold_in(step)` is provided for that; the eager path uses the global state.
"""
import time

import jax
import numpy as np

from .. import flags as _flags


class Generator:
    def __init__(self, seed=None):
        if seed is None:
            seed = np.uint32(int(time.time() * 1e6) & 0xFFFFFFFF)
        self._seed = int(seed)
        self._key = None  # lazy: creating a key initializes the jax backend
        self._offset = 0

    def manual_seed(self, seed):
        self._seed = int(seed)
        self._key = None
        self._offset = 0
        return self

    def seed(self):
        return self._seed

    def initial_seed(self):
        return self._seed

    def split(self):
        """Return a fresh subkey, advancing internal state.

        Inside a traced_rng scope (a jitted train step), subkeys derive from
        the TRACED step key instead — otherwise the key drawn at trace time
        bakes into the compiled program and every step reuses the same
        dropout masks."""
        if _TRACED_RNG:
            scope = _TRACED_RNG[-1]
            scope["key"], sub = jax.random.split(scope["key"])
            return sub
        if self._key is None:
            self._key = jax.random.key(self._seed)
        self._key, sub = jax.random.split(self._key)
        self._offset += 1
        return sub

    def fold_in(self, data):
        """Pure derivation of a key from the base seed — safe under jit tracing."""
        return jax.random.fold_in(jax.random.key(self._seed), data)


# FLAGS_seed seeds the default generator at import (env: FLAGS_seed=N);
# paddle.seed() overrides it at runtime — unset, this is Generator(0)
_DEFAULT = Generator(int(_flags.get_flag("seed", 0)))


def default_generator():
    return _DEFAULT


def seed(s):
    """paddle.seed parity."""
    _DEFAULT.manual_seed(s)
    return _DEFAULT


def get_rng_key():
    return _DEFAULT.split()


# -- traced RNG scope (jitted train steps thread a per-step key) --------------
import contextlib as _contextlib

_TRACED_RNG = []


@_contextlib.contextmanager
def traced_rng(key):
    """All Generator.split() calls inside derive from `key` (a traced PRNG
    key fed as a step argument), so compiled programs get fresh randomness
    every step instead of a trace-time constant."""
    _TRACED_RNG.append({"key": key})
    try:
        yield
    finally:
        _TRACED_RNG.pop()
