"""Functionalization helper: run a Layer as a pure function of its params.

The swap-and-restore of `Tensor._data` is the trickiest invariant in the
eager<->jit bridge (a leaked tracer in a Layer poisons every later eager
call); every jitted path (SpmdTrainer, hapi eval, static export) must go
through this one implementation.
"""
import contextlib


@contextlib.contextmanager
def functional_state(layer, params, buffers=None):
    """Temporarily bind `params`/`buffers` (name -> raw array) into the
    Layer's tensors; ALWAYS restores the originals, even on trace errors."""
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    saved = {n: t._data for n, t in {**named_p, **named_b}.items()}
    try:
        for n, v in params.items():
            if n in named_p:
                named_p[n]._data = v
        for n, v in (buffers or {}).items():
            if n in named_b:
                named_b[n]._data = v
        yield named_p, named_b
    finally:
        for n, t in {**named_p, **named_b}.items():
            t._data = saved[n]
