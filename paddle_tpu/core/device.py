"""Device/Place abstraction.

Reference parity: paddle/fluid/platform/place.h:26-103 (CPUPlace/CUDAPlace/XPUPlace +
boost::variant Place) and DeviceContextPool (platform/device_context.h:695).
TPU-native design: a Place is a thin view over a jax.Device; there is no DeviceContext /
stream management — XLA owns scheduling. `set_device` picks the default device used by
tensor-creation ops (jax.default_device).
"""
import jax


class Place:
    """Base place. Equality is by device kind + index."""

    kind = "undefined"

    def __init__(self, device_id=0):
        self._device_id = int(device_id)

    def get_device_id(self):
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.kind, self._device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self._device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # fall back to CPU host devices
            devs = jax.devices("cpu")
        return devs[min(self._device_id, len(devs) - 1)]


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    kind = "tpu"


class CUDAPlace(Place):  # accepted for API compat; maps to the accelerator if present
    kind = "tpu"


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


def _kind_of(jdev):
    plat = jdev.platform.lower()
    if plat in ("tpu", "axon"):
        return "tpu"
    if plat in ("gpu", "cuda", "rocm"):
        return "gpu"
    return "cpu"


_CURRENT = [None]


def _default_place():
    for d in jax.devices():
        if _kind_of(d) == "tpu":
            return TPUPlace(0)
    return CPUPlace(0)


def set_device(device):
    """paddle.set_device('tpu'|'cpu'|'tpu:0'|'gpu') parity
    (python/paddle/fluid/framework.py _current_expected_place)."""
    if isinstance(device, Place):
        _CURRENT[0] = device
        return device
    name = str(device).lower()
    idx = 0
    if ":" in name:
        name, idx_s = name.split(":", 1)
        idx = int(idx_s)
    if name in ("tpu", "gpu", "cuda", "xpu", "npu"):
        place = TPUPlace(idx)
    elif name == "cpu":
        place = CPUPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    _CURRENT[0] = place
    return place


def get_device():
    p = current_place()
    return f"{p.kind}:{p.get_device_id()}"


def current_place():
    if _CURRENT[0] is None:
        _CURRENT[0] = _default_place()
    return _CURRENT[0]


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return True


def device_count():
    return len(jax.devices())
