"""Dtype registry for paddle_tpu.

Reference parity: paddle/fluid/framework/framework.proto:106 (VarType.Type) defines the
dtype taxonomy (BOOL..COMPLEX128); python/paddle/fluid/data_feeder.py convert_dtype.
TPU-native design: dtypes are jnp dtypes directly; bfloat16 is first-class (MXU native),
float64 is supported but discouraged on TPU.
"""
import numpy as np
import jax.numpy as jnp

# Canonical dtype objects are numpy dtype instances (what jnp uses natively).
bool_ = jnp.bool_.dtype if hasattr(jnp.bool_, "dtype") else np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16.dtype if hasattr(jnp.bfloat16, "dtype") else np.dtype(jnp.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_NAME_TO_DTYPE = {
    "bool": np.dtype("bool"),
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


def convert_dtype(dtype):
    """Normalize a user-provided dtype (str | np.dtype | jnp dtype | None) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise TypeError(f"Unsupported dtype string: {dtype!r}")
        return _NAME_TO_DTYPE[dtype]
    try:
        return np.dtype(dtype)
    except TypeError:
        raise TypeError(f"Unsupported dtype: {dtype!r}")


def dtype_name(dtype):
    d = convert_dtype(dtype)
    if d == bfloat16:
        return "bfloat16"
    return d.name


def is_floating(dtype):
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype):
    return convert_dtype(dtype) in _INTEGER


def is_complex(dtype):
    return convert_dtype(dtype) in _COMPLEX


_DEFAULT_DTYPE = [float32]


def set_default_dtype(d):
    """paddle.set_default_dtype parity (python/paddle/framework/framework.py)."""
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError("set_default_dtype only supports floating dtypes")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]
