"""Eager Tensor: the dygraph VarBase equivalent.

Reference parity: paddle/fluid/imperative/layer.h:65 (VarBase — data + grad var +
stop_gradient + hooks), python/paddle/fluid/dygraph/math_op_patch.py (operator overloads),
varbase_patch_methods.py:136 (backward()).

TPU-native design: a Tensor wraps a jax.Array (which may be a tracer inside jit — the same
class flows through eager and traced code). Ops are pure jnp functions run through
`apply()`, which records a vjp pullback on the global tape when grads are needed. In-place
ops rebind `_data` (functional under the hood, mutable at the API).
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .device import current_place
from .tape import Node, global_tape

_SCALAR_TYPES = (int, float, bool, np.number, np.bool_)

_HOST_SYNC_STAT = [None]  # lazy: core must import before the monitor package


def _host_sync_counter():
    c = _HOST_SYNC_STAT[0]
    if c is None:
        from ..monitor import counter

        c = _HOST_SYNC_STAT[0] = counter(
            "host_sync_total",
            "device->host pulls through Tensor._to_host "
            "(.numpy()/.item()/.tolist()/bool()/int()/float())")
    return c


def _is_tensor(x):
    return isinstance(x, Tensor)


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "grad",
        "_node",
        "name",
        "persistable",
        "retain_grads",
        "_hooks",
        "__weakref__",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            data = np.asarray(data)
            if dtype is None and data.dtype == np.float64:
                data = data.astype(dtype_mod.get_default_dtype())
            data = jnp.asarray(data, dtype=dtype_mod.convert_dtype(dtype))
        elif dtype is not None and data.dtype != dtype_mod.convert_dtype(dtype):
            data = data.astype(dtype_mod.convert_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self.name = name or ""
        self.persistable = False
        self.retain_grads = False
        self._hooks = None

    # ---- basic properties ----------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    ndimension = ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        return current_place()

    @property
    def T(self):
        from .dispatch import apply

        return apply(lambda x: jnp.transpose(x), self)

    @property
    def is_leaf(self):
        return self._node is None

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    def rank(self):
        return self.ndim

    def numpy(self):
        return self._to_host()

    def item(self, *args):
        if args:
            return self._to_host().item(*args)
        return self._to_host().item()

    def tolist(self):
        return self._to_host().tolist()

    def _to_host(self):
        """Single device->host chokepoint behind numpy()/item()/tolist()/
        __bool__/__int__/__float__ — THE sync the analysis layer polices.

        Inside a jax trace the value is abstract, so a host pull can never
        succeed; FLAGS_trace_host_sync picks what happens before jax's own
        (opaque) tracer error: "silent" (default — prior behavior),
        "warn" (explain the sync, then let jax raise), or "error" (raise
        immediately with the framework-level message). Eager tensors are
        unaffected in every mode.
        """
        data = self._data
        _host_sync_counter().inc()
        if _is_tracer(data):
            from .. import flags as _flags

            mode = _flags.get_flag("trace_host_sync", "silent")
            if mode in ("warn", "error"):
                msg = ("Tensor host sync (.numpy()/.item()/.tolist()/"
                       "bool()/int()/float()) inside a traced function: "
                       "the value is abstract at trace time and each call "
                       "would block the device stream at run time. Return "
                       "the tensor from the jitted function instead, or "
                       "use jax.debug hooks for prints.")
                if mode == "error":
                    raise RuntimeError(msg)
                import warnings

                warnings.warn(msg, stacklevel=3)
        return np.asarray(data)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __repr__(self):
        try:
            from ..tensor.to_string import array_repr
        except ImportError:  # early-import repr before the package finishes
            body = repr(np.asarray(self._data))
        else:
            body = array_repr(self._data)
        return (
            f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}, "
            f"stop_gradient={self.stop_gradient},\n       {body})"
        )

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__()

    # ---- autograd ------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False,
                 create_graph=False):
        from .tape import backward as _backward

        _backward([self], [grad_tensor], retain_graph=retain_graph,
                  create_graph=create_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def retain_grad(self):
        self.retain_grads = True

    def detach(self):
        t = Tensor.__new__(Tensor)
        t._data = self._data
        t.stop_gradient = True
        t.grad = None
        t._node = None
        t.name = self.name
        t.persistable = self.persistable
        t.retain_grads = False
        t._hooks = None
        return t

    def clone(self):
        from .dispatch import apply

        return apply(lambda x: x + jnp.zeros_like(x), self)

    def register_hook(self, hook):
        """VarBase hook parity (imperative/hooks.h); applied to .grad on accumulate."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        return hook

    def _accumulate_grad(self, cot):
        if cot.dtype != self._data.dtype:
            cot = cot.astype(self._data.dtype)
        if self._hooks:
            g = Tensor(cot, stop_gradient=True)
            for h in self._hooks:
                out = h(g)
                if out is not None:
                    g = out
            cot = g._data
        if self.grad is None:
            self.grad = Tensor(cot, stop_gradient=True)
        else:
            self.grad = Tensor(self.grad._data + cot, stop_gradient=True)

    # ---- mutation ------------------------------------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}"
            )
        self._data = value

    def copy_(self, other, *a):
        self.set_value(other)
        return self

    def astype(self, dtype):
        from .dispatch import apply

        d = dtype_mod.convert_dtype(dtype)
        return apply(lambda x: x.astype(d), self)

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # device moves are XLA-managed; only dtype casts are meaningful
        for a in args:
            try:
                return self.astype(a)
            except TypeError:
                continue
        return self

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    # ---- indexing ------------------------------------------------------------
    def __getitem__(self, idx):
        from .dispatch import apply

        idx = _unwrap_index(idx)
        return apply(lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            value = value._data
        self._data = self._data.at[idx].set(value)

    # ---- python operators are patched in tensor/math_patch.py -----------------


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


class ParamBase(Tensor):
    """Trainable parameter (python/paddle/fluid/framework.py:5430 ParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "is_distributed", "spmd_spec")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.spmd_spec = None  # PartitionSpec for tensor-parallel layers (TPU-native)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (python/paddle/tensor/creation.py to_tensor)."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
