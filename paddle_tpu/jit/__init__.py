"""paddle.jit parity (python/paddle/jit: @to_static, save, load, TracedLayer).

Reference parity: fluid/dygraph/dygraph_to_static/ (ProgramTranslator:756 AST rewriting
into ProgramDesc) and fluid/dygraph/jit.py:160 declarative.

TPU-native design: no AST transform needed — `to_static` wraps the function/Layer in
jax.jit over its functional view (params+buffers as pytree inputs), with InputSpec-driven
shape specialization. jit.save exports params + a StableHLO text of the traced program;
jit.load restores a callable TranslatedLayer.
"""
import functools
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tape import global_tape
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


class InputSpec:
    """python/paddle/static/input.py InputSpec parity."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _tensorize(x):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (jnp.ndarray, np.ndarray)) or hasattr(x, "dtype"):
        return Tensor(x)
    return x


class StaticFunction:
    """The @to_static wrapper (dygraph_to_static/program_translator.py StaticFunction
    parity): caches one compiled XLA program per input signature."""

    def __init__(self, fn, input_spec=None, layer=None):
        self._orig_fn = fn
        self._fn = self._maybe_dy2static(fn)
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, fn)

    @staticmethod
    def _maybe_dy2static(fn):
        """Rewrite tensor-dependent if/while into lax.cond/while_loop
        (dygraph_to_static transformer parity); fall back to plain tracing."""
        try:
            from .dy2static import transform_function

            base = fn.__func__ if hasattr(fn, "__func__") else fn
            new, n = transform_function(base)
            if n == 0:
                return fn
            if hasattr(fn, "__self__"):
                return new.__get__(fn.__self__)
            return new
        except Exception:
            return fn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        # cache the bound wrapper per instance: a fresh StaticFunction per
        # attribute access would discard its jit cache and _eager_fallback
        # state, re-tracing (and re-warning) on every call
        key = "__static_fn_" + getattr(self._orig_fn, "__name__", "fn")
        try:
            cached = instance.__dict__.get(key)
        except AttributeError:  # instance without __dict__ (slots)
            return StaticFunction(self._orig_fn.__get__(instance, owner),
                                  self._input_spec, layer=instance)
        if cached is None:
            cached = StaticFunction(self._orig_fn.__get__(instance, owner),
                                    self._input_spec, layer=instance)
            instance.__dict__[key] = cached
        return cached

    def _resolve_layer(self, args):
        if self._layer is not None:
            return self._layer, args
        if args and isinstance(args[0], Layer):
            return args[0], args[1:]
        return None, args

    def __call__(self, *args, **kwargs):
        layer, call_args = self._resolve_layer(args)
        tensor_args = [_tensorize(a) for a in call_args]
        if getattr(self, "_eager_fallback", False) or not ProgramTranslator._enabled:
            # ProgramTranslator.enable(False): run the original function
            # eagerly (reference StaticFunction._decorated_function fallback)
            return self._orig_fn(*tensor_args, **kwargs)
        key_parts = []
        for a in tensor_args:
            if isinstance(a, Tensor):
                key_parts.append(("T", tuple(a.shape), str(a.dtype)))
            else:
                key_parts.append(("O", repr(a)))
        training = layer.training if layer is not None else True
        key = (tuple(key_parts), tuple(sorted(kwargs.items())), training)

        if key not in self._cache:
            self._cache[key] = self._build(layer, tensor_args, kwargs, training)
        compiled, param_names, buffer_names = self._cache[key]

        if layer is not None:
            params = {n: p._data for n, p in layer.named_parameters()}
            buffers = {n: b._data for n, b in layer.named_buffers()}
        else:
            params, buffers = {}, {}
        arr_args = [a._data if isinstance(a, Tensor) else a for a in tensor_args]
        try:
            out = compiled(params, buffers, *arr_args)
        except Exception as e:
            from .dy2static import Dy2StCarryError

            # the rewritten control flow can fail only at trace time (a local
            # the carry can't hold, a branch-structure mismatch): fall back to
            # dygraph — run the original function eagerly, the reference's
            # ProgramTranslator fallback semantics
            if self._fn is self._orig_fn or not isinstance(
                    e, (Dy2StCarryError, NameError)):
                raise
            import warnings

            warnings.warn(
                f"dy2static transform of '{getattr(self._orig_fn, '__name__', '?')}' "
                f"failed at trace time ({type(e).__name__}: {e}); falling back "
                "to eager (dygraph) execution")
            self._fn = self._orig_fn
            self._cache.clear()
            self._eager_fallback = True
            return self._orig_fn(*tensor_args, **kwargs)
        return jax.tree_util.tree_map(
            lambda v: Tensor(v), out, is_leaf=lambda v: isinstance(v, (jax.Array, np.ndarray))
        )

    def _build(self, layer, tensor_args, kwargs, training):
        fn = self._fn
        tape = global_tape()

        def pure(params, buffers, *arr_args):
            wrapped = [Tensor(a) if isinstance(a, (jax.Array, np.ndarray)) or hasattr(a, "dtype") else a for a in arr_args]
            with tape.pause():
                if layer is not None:
                    named_p = dict(layer.named_parameters())
                    named_b = dict(layer.named_buffers())
                    saved = {n: t._data for n, t in {**named_p, **named_b}.items()}
                    try:
                        for n, v in params.items():
                            named_p[n]._data = v
                        for n, v in buffers.items():
                            named_b[n]._data = v
                        out = fn(*wrapped, **kwargs)
                    finally:
                        for n, t in {**named_p, **named_b}.items():
                            t._data = saved[n]
                else:
                    out = fn(*wrapped, **kwargs)
            return jax.tree_util.tree_map(
                lambda v: v._data if isinstance(v, Tensor) else v, out,
                is_leaf=lambda v: isinstance(v, Tensor),
            )

        compiled = jax.jit(pure)
        pn = [n for n, _ in layer.named_parameters()] if layer is not None else []
        bn = [n for n, _ in layer.named_buffers()] if layer is not None else []
        return compiled, pn, bn

    def concrete_program(self, *args):
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """paddle.jit.to_static parity (fluid/dygraph/jit.py:160 declarative)."""

    def decorate(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec, layer=fn)
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


class TranslatedLayer(Layer):
    """jit.load product (fluid/dygraph/io.py TranslatedLayer parity)."""

    def __init__(self, program_fn, state):
        super().__init__()
        self._program_fn = program_fn
        from ..core.tensor import ParamBase

        for n, v in state.items():
            self.add_parameter(n.replace(".", "__"), ParamBase(v))
        self._orig_names = list(state.keys())

    def forward(self, *args):
        params = {n: self._parameters[n.replace(".", "__")]._data for n in self._orig_names}
        arr_args = [a._data if isinstance(a, Tensor) else a for a in args]
        out = self._program_fn(params, *arr_args)
        return jax.tree_util.tree_map(lambda v: Tensor(v), out,
                                      is_leaf=lambda v: isinstance(v, (jax.Array, np.ndarray)))


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity (fluid/dygraph/jit.py:160 + dygraph/io.py).

    Durable path: when an input spec is available (explicit `input_spec=` or
    recorded on a @to_static forward), the program is exported via jax.export
    (static/io.py) — params npz + serialized StableHLO artifact that
    `jit.load` runs WITHOUT the original class definition. A pickled Layer is
    written as a fallback only (shape-polymorphic re-trace path)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # a previous save's durable artifact must never shadow this save: load()
    # prefers .pdmodel.jaxexport, so a stale one would serve the OLD model
    for stale in (".pdmodel.jaxexport", ".pdiparams.npz"):
        try:
            os.remove(path + stale)
        except FileNotFoundError:
            pass

    spec = input_spec
    if spec is None and isinstance(getattr(layer, "forward", None),
                                   StaticFunction):
        spec = layer.forward._input_spec
    exported = False
    if spec is not None:
        from ..static.io import save_inference_model

        class _Var:  # shape/dtype carrier for save_inference_model
            def __init__(self, shape, dtype):
                self.shape = tuple(shape)  # None dims -> symbolic export
                self.dtype = dtype

        feed_vars = [_Var(s.shape, getattr(s, "dtype", "float32"))
                     for s in _to_spec_list(spec)]
        try:
            res = save_inference_model(path, feed_vars, None, layer=layer)
            exported = bool(isinstance(res, dict) and res.get("exported"))
        except Exception as e:
            import warnings

            warnings.warn(
                f"jit.save: durable export failed ({type(e).__name__}: {e}); "
                "falling back to the pickled-Layer artifact only")

    if not exported:
        # fallback path needs the params pickle; when the durable artifact
        # was written the weights already live in .pdiparams.npz — don't
        # serialize a multi-GB state twice
        state = {n: np.asarray(t._data) for n, t in layer.state_dict().items()}
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f, protocol=4)
    try:
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(layer, f, protocol=4)
    except Exception:
        # layer not picklable: durable artifact above is the only program
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(None, f)


def _to_spec_list(spec):
    specs = spec if isinstance(spec, (list, tuple)) else [spec]
    out = []
    for s in specs:
        if isinstance(s, InputSpec):
            out.append(s)
        elif isinstance(s, Tensor):
            out.append(InputSpec(s.shape, str(s.dtype)))
        else:
            out.append(InputSpec(tuple(s.shape), str(getattr(s, "dtype", "float32"))))
    return out


def load(path, **configs):
    """jit.load parity: prefers the durable jax.export artifact — no python
    class needed; falls back to the pickled Layer (requires the class)."""
    if os.path.exists(path + ".pdmodel.jaxexport"):
        from ..static.io import _load_exported

        exported, params = _load_exported(path)

        def program_fn(params_d, *args):
            return exported.call({k: jnp.asarray(v)
                                  for k, v in params_d.items()}, *args)

        return TranslatedLayer(program_fn, params)
    with open(path + ".pdmodel", "rb") as f:
        layer = pickle.load(f)
    if layer is None:
        raise RuntimeError(
            "saved model is not loadable: no jax.export artifact and the "
            "Layer was not picklable — re-save with input_spec= for a "
            "durable export")
    if os.path.exists(path + ".pdiparams"):
        with open(path + ".pdiparams", "rb") as f:
            layer.set_state_dict(pickle.load(f))
    # else: the pickled layer already carries its weights
    return layer


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TracedLayer:
    """fluid/dygraph/jit.py TracedLayer parity (imperative trace -> static program)."""

    def __init__(self, layer, fn):
        self._layer = layer
        self._fn = fn

    @staticmethod
    def trace(layer, inputs):
        sf = StaticFunction(layer.forward, layer=layer)
        out = sf(*inputs)
        return out, TracedLayer(layer, sf)

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        save(self._layer, path)


def set_code_level(level=100):
    """Compat (dygraph_to_static logging): records the desired level."""
    import os

    os.environ["PADDLE_TPU_D2S_CODE_LEVEL"] = str(level)


def set_verbosity(level=0, also_to_stdout=False):
    import os

    os.environ["PADDLE_TPU_D2S_VERBOSITY"] = str(level)


from . import aot  # noqa: E402,F401  (persistent AOT compile-cache façade)


class ProgramTranslator:
    """Compat singleton (dygraph_to_static ProgramTranslator): enable()
    toggles whether @to_static transforms or falls straight through."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static=True):
        ProgramTranslator._enabled = bool(enable_to_static)
