"""dy2static control-flow transforms.

Reference parity: fluid/dygraph/dygraph_to_static/ — `IfElseTransformer`
(ifelse_transformer.py) and `LoopTransformer` (loop_transformer.py) rewrite
tensor-dependent python control flow into graph ops (`cond`, `while_loop`)
inside `@to_static`; `convert_ifelse`/`convert_while_loop` are the runtime
dispatchers (convert_operators.py) that fall back to plain python control flow
when the predicate is a host value.

TPU-native design: the rewrite targets `jax.lax.cond` / `jax.lax.while_loop`
(compiled, MXU-friendly control flow — SURVEY.md "no data-dependent Python
control flow inside jit"). Scope is the structured subset that covers real
model code:
  - `if`/`elif`/`else` whose branches assign locals (no return/break inside),
  - `while` loops whose bodies assign locals (no break/continue/return).
Anything else — or any function we cannot re-compile (closures, missing
source) — is left untouched and falls back to plain tracing, which is already
correct for host-value predicates.
"""
import ast
import inspect
import textwrap

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["convert_ifelse", "convert_while_loop", "transform_function",
           "convert_logical_not", "convert_logical_and", "Dy2StCarryError"]


class Dy2StCarryError(TypeError):
    """A rewritten control-flow region swept a value into its carry that lax
    control flow cannot hold (e.g. None, a string, an object). StaticFunction
    catches this and re-traces with the untransformed function."""


def _is_traced(x):
    return isinstance(x, Tensor) or isinstance(x, jax.core.Tracer) or (
        hasattr(x, "dtype") and hasattr(x, "shape") and not isinstance(x, bool))


def _raw(x):
    if isinstance(x, Tensor):
        return x._data
    return x


def _to_carry(vals):
    """Carry elements through lax control flow as arrays; remember wrappers."""
    raws, kinds = [], []
    for v in vals:
        if isinstance(v, Tensor):
            raws.append(v._data)
            kinds.append("tensor")
        elif isinstance(v, (bool, int, float)) or hasattr(v, "dtype"):
            raws.append(jnp.asarray(v))
            kinds.append("array")
        else:
            raise Dy2StCarryError(
                f"unsupported carry value {type(v).__name__}")
    return tuple(raws), kinds


def _from_carry(raws, kinds):
    out = []
    for r, k in zip(raws, kinds):
        out.append(Tensor(r) if k == "tensor" else r)
    return tuple(out)


def convert_ifelse(pred, true_fn, false_fn, seed=()):
    """Runtime dispatch for rewritten `if`: lax.cond when pred is traced.

    `seed` carries the pre-branch values of names either branch may read or
    rebind (so aug-assigns see the outer binding); branch fns take it as their
    single argument."""
    p = _raw(pred)
    if not _is_traced(p):
        return true_fn(seed) if p else false_fn(seed)

    seed_raws, seed_kinds = _to_carry(seed)
    kinds_box = {}

    def wrap(fn, tag):
        def pure(raw_seed):
            out = fn(_from_carry(raw_seed, seed_kinds))
            out = out if isinstance(out, tuple) else (out,)
            raws, kinds = _to_carry(out)
            kinds_box[tag] = kinds
            return raws
        return pure

    try:
        raws = jax.lax.cond(jnp.asarray(p).astype(bool), wrap(true_fn, "t"),
                            wrap(false_fn, "f"), seed_raws)
    except TypeError as e:
        # branch output structure mismatch (shape/dtype) from lax.cond:
        # the rewrite is unsuitable — signal StaticFunction to fall back
        raise Dy2StCarryError(f"cond branch structure mismatch: {e}") from e
    if kinds_box.get("t") != kinds_box.get("f"):
        raise Dy2StCarryError(
            "convert_ifelse branches returned different value kinds "
            f"({kinds_box.get('t')} vs {kinds_box.get('f')}); both branches "
            "must produce the same Tensor/array structure")
    return _from_carry(raws, kinds_box["t"])


def convert_while_loop(cond_fn, body_fn, carry):
    """Runtime dispatch for rewritten `while`: lax.while_loop when the
    condition is traced. Carried values become arrays (ints/floats included),
    matching the reference's tensor-loop-var semantics.

    Traced-ness is re-checked EVERY host iteration, not just the first: a
    lowered `while True: ... if tensor_pred: break` starts with a pure-host
    condition (break flag False, test True) and only becomes traced once the
    body computes the flag — the loop must switch to lax at that point."""
    while True:
        c = cond_fn(carry)
        if _is_traced(_raw(c)):
            break
        if not c:
            return carry
        carry = body_fn(carry)

    raws, kinds = _to_carry(carry)

    def cond(raw_carry):
        c = cond_fn(_from_carry(raw_carry, kinds))
        return jnp.asarray(_raw(c)).astype(bool)

    def body(raw_carry):
        out = body_fn(_from_carry(raw_carry, kinds))
        new_raws, _ = _to_carry(out)
        return new_raws

    try:
        final = jax.lax.while_loop(cond, body, raws)
    except TypeError as e:
        raise Dy2StCarryError(f"while carry structure mismatch: {e}") from e
    return _from_carry(final, kinds)


def convert_logical_not(x):
    """Runtime `not` that stays traced for tensors (convert_operators.py
    convert_logical_not parity)."""
    r = _raw(x)
    if _is_traced(r):
        return jnp.logical_not(r)
    return not r


def convert_logical_and(a, b):
    """Short-circuiting: `b` may be a thunk — it is only evaluated when `a`
    is traced or host-truthy, preserving python `and` semantics (the lowered
    loop test must not re-evaluate the original condition after a break flag
    fires — e.g. an index probe that is only safe while in bounds)."""
    r_a = _raw(a)
    if not _is_traced(r_a) and not r_a:
        return False
    r_b = _raw(b() if callable(b) else b)
    if _is_traced(r_a) or _is_traced(r_b):
        return jnp.logical_and(jnp.asarray(r_a).astype(bool),
                               jnp.asarray(r_b).astype(bool))
    return r_a and r_b


# ---------------- AST rewrite -------------------------------------------------

_BAD_IF = (ast.Return, ast.Break, ast.Continue, ast.Yield, ast.YieldFrom)
_BAD_LOOP = _BAD_IF


def _contains(nodes, kinds):
    """True if any node of `kinds` appears in the CURRENT scope (a Return in
    a nested def — e.g. an already-generated __dy2st_* helper — is its own
    scope's concern, not the enclosing control flow's)."""
    def walk(n):
        if isinstance(n, kinds):
            return True
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if walk(child):
                return True
        return False

    return any(walk(n) for n in nodes
               if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)))


def _assigned_names(nodes):
    """Plain-Name assignment targets in a statement list (incl. aug-assign)."""
    names = set()
    for n in nodes:
        names |= _scoped_assigned(n)
    return names


def _target_names(t):
    """Local names bound by an assignment target. Subscript/Attribute targets
    bind nothing (`d[k] = v` must not collect `k`)."""
    if isinstance(t, ast.Name):
        return {t.id}
    if isinstance(t, (ast.Tuple, ast.List)):
        out = set()
        for e in t.elts:
            out |= _target_names(e)
        return out
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return set()


def _scoped_assigned(node):
    """Names bound by `node` in the CURRENT scope — does not descend into
    nested function/class scopes, and skips generated __dy2st_* helpers."""
    names = set()
    if isinstance(node, ast.Assign):
        for t in node.targets:
            names |= _target_names(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        names |= _target_names(node.target)
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        names |= _target_names(node.target)
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            if item.optional_vars is not None:
                names |= _target_names(item.optional_vars)
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        if not node.name.startswith("__dy2st_"):
            names.add(node.name)
        return names  # do not descend into the nested scope
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            if not child.name.startswith("__dy2st_"):
                names.add(child.name)
            continue
        names |= _scoped_assigned(child)
    return names


def _must_bound(st):
    """Names SURELY bound after `st` executes (must-analysis): an If only
    guarantees names both branches bind; a loop body may run zero times, a
    Try may bail early — those guarantee nothing."""
    if isinstance(st, ast.If):
        t = set()
        for s in st.body:
            t |= _must_bound(s)
        f = set()
        for s in st.orelse:
            f |= _must_bound(s)
        return t & f
    if isinstance(st, (ast.While, ast.For, ast.AsyncFor, ast.Try)):
        return set()
    if isinstance(st, (ast.With, ast.AsyncWith)):
        out = set()
        for item in st.items:
            if item.optional_vars is not None:
                out |= _target_names(item.optional_vars)
        for s in st.body:
            out |= _must_bound(s)
        return out
    return _scoped_assigned(st)


def _annotate_bound_before(fdef):
    """Attach `_bound_before` (names SURELY bound when control reaches the
    node — must-analysis, not may) to every If/While in the function scope.
    May-bound would sweep a conditionally-assigned local into the carry and
    NameError at runtime when the binding branch wasn't taken."""
    bound = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args
                             + fdef.args.kwonlyargs)}
    if fdef.args.vararg:
        bound.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        bound.add(fdef.args.kwarg.arg)

    def walk(stmts, bound, maybe):
        for st in stmts:
            if isinstance(st, (ast.If, ast.While)):
                st._bound_before = set(bound)
                # may-bound-but-not-must names are the danger zone: a rewrite
                # must not classify them as loop-local temporaries (their
                # writes would be silently discarded when the name IS bound)
                st._maybound_before = set(maybe)
            if isinstance(st, ast.If):
                walk(st.body, set(bound), set(maybe))
                walk(st.orelse, set(bound), set(maybe))
            elif isinstance(st, (ast.While, ast.For)):
                inner = set(bound)
                if isinstance(st, ast.For):
                    inner |= _target_names(st.target)
                walk(st.body, inner, set(maybe) | inner)
                walk(st.orelse, set(bound), set(maybe))
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                walk(st.body, bound, maybe)
            elif isinstance(st, ast.Try):
                for blk in (st.body, st.orelse, st.finalbody):
                    walk(blk, set(bound), set(maybe))
                for h in st.handlers:
                    walk(h.body, set(bound), set(maybe))
            bound |= _must_bound(st)
            maybe |= _scoped_assigned(st)

    walk(fdef.body, bound, set(bound))


class _LoopLowering(ast.NodeTransformer):
    """Pass 1 (LoopTransformer parity, loop_transformer.py): desugar
    `for i in range(...)` into while, and lower `if p: break/continue`
    into flag-guarded form — pure python-semantics-preserving rewrites, so
    pass 3 can treat every loop as a plain while. Unsupported loop shapes
    are left untouched and reported via `skipped`."""

    def __init__(self):
        self.counter = 0
        self.skipped = []  # (construct, lineno)

    def _skip(self, node, construct):
        self.skipped.append((construct, getattr(node, "lineno", 0)))
        return node

    # -- for-range desugaring --------------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        # non-range/host iterations unroll fine under plain tracing — no
        # warning; only range() shapes we ALMOST handled are worth reporting
        if node.orelse:
            return node
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords):
            return node
        if not isinstance(node.target, ast.Name):
            return self._skip(node, "for-range with a tuple target")
        a = ast.Constant(value=0)
        s = ast.Constant(value=1)
        if len(it.args) == 1:
            b = it.args[0]
        elif len(it.args) == 2:
            a, b = it.args
        elif len(it.args) == 3:
            a, b, s = it.args
            if not (isinstance(s, ast.Constant) and isinstance(s.value, int)):
                return self._skip(node, "for-range with a non-literal step")
        else:
            return self._skip(node, "malformed range()")
        step_neg = isinstance(s, ast.Constant) and isinstance(s.value, int) \
            and s.value < 0
        i = node.target.id
        n = self.counter
        self.counter += 1
        # python range semantics: a hidden counter advances BEFORE the user
        # body (continue-safe, body reassignment of `i` cannot derail the
        # iteration, and after the loop `i` holds the last yielded value)
        bname = f"__dy2st_bound_{n}"
        cname = f"__dy2st_it_{n}"
        cmp_op = ast.Gt() if step_neg else ast.Lt()
        test = ast.Compare(left=ast.Name(id=cname, ctx=ast.Load()),
                           ops=[cmp_op],
                           comparators=[ast.Name(id=bname, ctx=ast.Load())])
        body = [
            ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                       value=ast.Name(id=cname, ctx=ast.Load())),
            ast.AugAssign(target=ast.Name(id=cname, ctx=ast.Store()),
                          op=ast.Add(), value=s),
        ] + list(node.body)
        while_node = ast.While(test=test, body=body, orelse=[])
        lowered = self._lower_while(while_node)
        out = [ast.Assign(targets=[ast.Name(id=bname, ctx=ast.Store())],
                          value=b),
               ast.Assign(targets=[ast.Name(id=cname, ctx=ast.Store())],
                          value=a),
               # pre-bind the loop var so it is carried out of a lax loop
               # (post-loop reads see the last yielded value, like python);
               # deviation: an empty range leaves it = start, not NameError
               ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                          value=ast.Name(id=cname, ctx=ast.Load()))]
        return out + (lowered if isinstance(lowered, list) else [lowered])

    # -- break/continue lowering ----------------------------------------------
    @staticmethod
    def _is_exit_if(st):
        return (isinstance(st, ast.If) and not st.orelse and len(st.body) == 1
                and isinstance(st.body[0], (ast.Break, ast.Continue)))

    def visit_While(self, node):
        self.generic_visit(node)
        return self._lower_while(node)

    def _lower_while(self, node):
        # children already visited (visit_While / visit_For both guarantee it)
        if node.orelse:
            return self._skip(node, "while-else")
        if not _contains(node.body, (ast.Break, ast.Continue)):
            return node
        # supported shape: every break/continue is a lone `if p: break`
        # at the TOP level of the loop body
        exits = sum(1 for st in node.body if self._is_exit_if(st))
        total = 0

        def count(nodes):
            nonlocal total
            for st in nodes:
                if isinstance(st, (ast.Break, ast.Continue)):
                    total += 1
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.ClassDef,
                                          ast.While, ast.For)):
                        continue  # other scope / inner loop owns its exits
                    count([child])

        count(node.body)
        if exits != total:
            # host-predicate loops run as plain python anyway — stay quiet
            if _host_only_pred(node.test):
                return node
            return self._skip(node, "break/continue not of the form "
                                    "'if <pred>: break' at loop-body top level")
        n = self.counter
        self.counter += 1
        brk = f"__dy2st_brk_{n}"
        has_break = False

        def guard(flag, rest):
            if not rest:
                return []
            return [ast.If(
                test=ast.Call(func=ast.Name(id="__dy2st_not", ctx=ast.Load()),
                              args=[ast.Name(id=flag, ctx=ast.Load())],
                              keywords=[]),
                body=rest, orelse=[])]

        def lower(stmts, depth):
            nonlocal has_break
            out = []
            for idx, st in enumerate(stmts):
                if self._is_exit_if(st):
                    is_brk = isinstance(st.body[0], ast.Break)
                    flag = brk if is_brk else f"__dy2st_cont_{n}_{depth}_{idx}"
                    if is_brk:
                        has_break = True
                    out.append(ast.Assign(
                        targets=[ast.Name(id=flag, ctx=ast.Store())],
                        value=st.test))
                    out.extend(guard(flag, lower(stmts[idx + 1:], depth + 1)))
                    return out
                out.append(st)
            return out

        node.body = lower(list(node.body), 0)
        pre = []
        if has_break:
            pre.append(ast.Assign(targets=[ast.Name(id=brk, ctx=ast.Store())],
                                  value=ast.Constant(value=False)))
            # original test passed as a THUNK: it must not re-evaluate once
            # the break flag fired (convert_logical_and short-circuits)
            test_thunk = ast.Lambda(args=_no_args(), body=node.test)
            node.test = ast.Call(
                func=ast.Name(id="__dy2st_and", ctx=ast.Load()),
                args=[ast.Call(func=ast.Name(id="__dy2st_not", ctx=ast.Load()),
                               args=[ast.Name(id=brk, ctx=ast.Load())],
                               keywords=[]),
                      test_thunk],
                keywords=[])
        return pre + [node] if pre else node


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.applied = 0
        self.skipped = []  # (construct, lineno)

    def _names_tuple(self, names, ctx):
        return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                         ctx=ctx())

    def _skip(self, node, construct):
        self.skipped.append((construct, getattr(node, "lineno", 0)))
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if _host_only_pred(node.test):
            return node  # `x is None` / `self.training`-style flags: plain if
        if _contains(node.body + node.orelse, _BAD_IF):
            return self._skip(node, "if containing return/break/continue/yield")
        bound_before = getattr(node, "_bound_before", set())
        a_true = _assigned_names(node.body)
        a_false = _assigned_names(node.orelse)
        assigned = a_true | a_false
        if not assigned:
            return node
        seed = sorted(assigned & bound_before)
        both = sorted((a_true & a_false) - set(seed))
        if set(seed) | set(both) != assigned:
            # a name assigned in only one branch with no prior binding: the
            # untaken branch could not return it
            return self._skip(
                node, "if assigning a name in only one branch with no "
                      "prior binding")
        names = seed + both
        i = self.counter
        self.counter += 1
        carry_arg = f"__dy2st_carry_{i}"
        # branch fns take the seed values as a carry tuple so reads (incl.
        # aug-assign reads) see the pre-branch bindings
        unpack = ([ast.Assign(targets=[self._names_tuple(seed, ast.Store)],
                              value=ast.Name(id=carry_arg, ctx=ast.Load()))]
                  if seed else [])
        ret = ast.Return(value=self._names_tuple(names, ast.Load))
        true_fn = ast.FunctionDef(
            name=f"__dy2st_true_{i}",
            body=[_copy_stmt(s) for s in unpack] + list(node.body) + [ret],
            args=_one_arg(carry_arg), decorator_list=[])
        false_fn = ast.FunctionDef(
            name=f"__dy2st_false_{i}",
            body=[_copy_stmt(s) for s in unpack] + list(node.orelse) + [
                ast.Return(value=self._names_tuple(names, ast.Load))],
            args=_one_arg(carry_arg), decorator_list=[])
        call = ast.Assign(
            targets=[self._names_tuple(names, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__dy2st_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=true_fn.name, ctx=ast.Load()),
                      ast.Name(id=false_fn.name, ctx=ast.Load()),
                      self._names_tuple(seed, ast.Load)],
                keywords=[]))
        self.applied += 1
        return [true_fn, false_fn, call]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _contains(node.body, _BAD_LOOP):
            if _host_only_pred(node.test):
                return node  # plain python loop: correct as-is, stay quiet
            return self._skip(
                node, "while with else or unlowered break/continue/return")
        bound_before = getattr(node, "_bound_before", set())
        maybound_before = getattr(node, "_maybound_before", set())
        assigned = _assigned_names(node.body)
        # a body write to a name that MAY be bound before the loop but is not
        # SURELY bound cannot be classified: as a carry it could NameError on
        # the unbound path, as a loop-local its write would be silently
        # dropped on the bound path — bail out, keep the python loop
        risky = (assigned & maybound_before) - bound_before
        if risky:
            return self._skip(
                node, f"while writing conditionally-bound name(s) "
                      f"{sorted(risky)}")
        # loop-local temporaries (never bound before the loop) stay local to
        # the body fn; the carry holds only pre-bound names
        names = sorted(assigned & bound_before)
        if not names:
            return node
        i = self.counter
        self.counter += 1
        carry_arg = f"__dy2st_carry_{i}"
        unpack = ast.Assign(
            targets=[self._names_tuple(names, ast.Store)],
            value=ast.Name(id=carry_arg, ctx=ast.Load()))
        cond_fn = ast.FunctionDef(
            name=f"__dy2st_cond_{i}",
            body=[unpack, ast.Return(value=node.test)],
            args=_one_arg(carry_arg), decorator_list=[])
        body_fn = ast.FunctionDef(
            name=f"__dy2st_body_{i}",
            body=[_copy_stmt(unpack)] + list(node.body) + [
                ast.Return(value=self._names_tuple(names, ast.Load))],
            args=_one_arg(carry_arg), decorator_list=[])
        call = ast.Assign(
            targets=[self._names_tuple(names, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__dy2st_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_fn.name, ctx=ast.Load()),
                      ast.Name(id=body_fn.name, ctx=ast.Load()),
                      self._names_tuple(names, ast.Load)],
                keywords=[]))
        self.applied += 1
        return [cond_fn, body_fn, call]


def _host_only_pred(test):
    """Predicates that are host flags, not tensors: `x is (not) None`, a bare
    name/attribute (`self.training`, `flag`), `not <host>`, `isinstance(...)`,
    or boolean combinations thereof."""
    if isinstance(test, (ast.Name, ast.Attribute, ast.Constant)):
        return True
    if isinstance(test, ast.Compare):
        if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in test.ops):
            return True
        return False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _host_only_pred(test.operand)
    if isinstance(test, ast.BoolOp):
        return all(_host_only_pred(v) for v in test.values)
    if isinstance(test, ast.Call):
        fn = test.func
        if isinstance(fn, ast.Name) and fn.id in ("isinstance", "hasattr",
                                                  "len", "callable"):
            return True
    return False


class _PrintAssertTransformer(ast.NodeTransformer):
    """PrintTransformer + AssertTransformer parity (dygraph_to_static/
    print_transformer.py, assert_transformer.py): `print(x)` on traced
    tensors becomes a compiled-side jax.debug.print; `assert cond[, msg]`
    becomes a host callback check (the reference lowers these to Print/Assert
    ops). Host-value prints/asserts keep plain python semantics at runtime —
    the dispatcher decides per call."""

    def __init__(self):
        self.applied = 0

    def visit_Call(self, node):
        self.generic_visit(node)
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            node.func = ast.Name(id="__dy2st_print", ctx=ast.Load())
            self.applied += 1
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        # msg passes as a zero-arg lambda so it is only evaluated on failure
        # (python assert semantics: a passing assert never computes its msg)
        msg = (ast.Lambda(args=_no_args(), body=node.msg)
               if node.msg is not None else ast.Constant(value=None))
        call = ast.Expr(value=ast.Call(
            func=ast.Name(id="__dy2st_assert", ctx=ast.Load()),
            args=[node.test, msg], keywords=[]))
        self.applied += 1
        return ast.copy_location(call, node)


def convert_print(*args, **kwargs):
    """Runtime dispatcher for rewritten print(): traced args print from the
    compiled program via jax.debug.print; host values print normally."""
    if any(_is_traced(a) for a in args):
        fmt = " ".join("{}" for _ in args)
        jax.debug.print(fmt, *[_raw(a) for a in args])
        return
    print(*args, **kwargs)


def convert_assert(test, msg=None):
    """Runtime dispatcher for rewritten assert: traced predicates check on
    host via debug callback (reference Assert op semantics: report + halt);
    host predicates assert normally. `msg` arrives as a zero-arg callable
    (lazy — only evaluated on failure, like python assert)."""
    def _msg():
        return msg() if callable(msg) else msg

    if _is_traced(test):
        # msg must evaluate NOW (trace time): deferring into the callback
        # would run it on leaked tracers. Tracer-safe msgs (f-strings of
        # shapes) work; ones needing concrete values fall back generically.
        try:
            m_val = _msg()
        except Exception:
            m_val = None

        def _check(ok):
            import numpy as _np

            ok_val = bool(_np.asarray(ok).all())
            if not ok_val:
                raise AssertionError(
                    m_val if m_val is not None
                    else "Assert failed in @to_static function")

        jax.debug.callback(_check, _raw(test))
        return
    if not test:
        m = _msg()
        raise AssertionError(m if m is not None else "")


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                         kw_defaults=[], defaults=[])


def _one_arg(name):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=name)],
                         kwonlyargs=[], kw_defaults=[], defaults=[])


def _copy_stmt(stmt):
    return ast.parse(ast.unparse(ast.fix_missing_locations(
        ast.Module(body=[stmt], type_ignores=[])))).body[0]


def transform_function(fn):
    """Rewrite tensor control flow in `fn`. Returns (new_fn, n_transforms);
    (fn, 0) when nothing applies or the function cannot be rewritten."""
    cached = getattr(fn, "__dy2static_cache__", None)
    if cached is not None:
        return cached  # (new_fn, n) memo — transform runs once per function
    if getattr(fn, "__closure__", None):
        return fn, 0  # cannot rebuild closure cells faithfully
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn, 0
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn, 0
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn, 0
    fdef.decorator_list = []  # decorators already applied to the original

    lower = _LoopLowering()
    lower.visit(tree)
    ast.fix_missing_locations(tree)
    _annotate_bound_before(fdef)
    tr = _ControlFlowTransformer()
    tr.visit(tree)
    pa = _PrintAssertTransformer()
    pa.visit(tree)
    skipped = {(c, ln) for c, ln in lower.skipped + tr.skipped}
    if skipped:
        import warnings

        details = "; ".join(f"line {ln}: {c}" for c, ln in sorted(
            skipped, key=lambda x: x[1]))
        warnings.warn(
            f"to_static({fn.__name__}): some control flow was not rewritten "
            f"to lax ops and will fall back to plain tracing — {details}")
    n_applied = tr.applied + pa.applied
    if n_applied == 0:
        try:
            fn.__dy2static_cache__ = (fn, 0)
        except (AttributeError, TypeError):
            pass
        return fn, 0
    ast.fix_missing_locations(tree)

    globs = dict(fn.__globals__)
    globs["__dy2st_ifelse"] = convert_ifelse
    globs["__dy2st_while"] = convert_while_loop
    globs["__dy2st_not"] = convert_logical_not
    globs["__dy2st_and"] = convert_logical_and
    globs["__dy2st_print"] = convert_print
    globs["__dy2st_assert"] = convert_assert
    code = compile(tree, filename=f"<dy2static:{fn.__name__}>", mode="exec")
    ns = {}
    exec(code, globs, ns)
    new_fn = ns[fdef.name]
    new_fn.__dy2static_transforms__ = n_applied
    try:
        fn.__dy2static_cache__ = (new_fn, n_applied)
    except (AttributeError, TypeError):
        pass
    return new_fn, n_applied
