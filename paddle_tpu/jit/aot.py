"""paddle_tpu.jit.aot — user-facing façade over framework/aot.py.

The persistent AOT executable cache lives in ``paddle_tpu.framework.aot``
(next to the other process-level framework services); this module is the
jit-namespace surface users reach for::

    from paddle_tpu.jit import aot

    paddle.set_flags({"jit_cache_dir": "/var/cache/paddle_tpu_aot"})
    step = aot.cached_jit(fn, site="user")      # jit + disk-backed compile
    step.warm(jax.ShapeDtypeStruct((8, 128), "int32"))   # data-free AOT

See docs/AOT.md for the cache-key contents, invalidation rules, and the
serve-deploy recipe (tools/aot_warm.py -> start engine).
"""
from ..framework.aot import (CachedJit, args_signature,  # noqa: F401
                             cache_dir, cached_jit, compile_cached,
                             enabled, mesh_fingerprint)

__all__ = ["CachedJit", "cached_jit", "compile_cached", "cache_dir",
           "enabled", "args_signature", "mesh_fingerprint"]
