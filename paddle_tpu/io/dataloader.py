"""DataLoader.

Reference parity: python/paddle/fluid/reader.py DataLoader +
fluid/dataloader/dataloader_iter.py (single-process iter :100 and
_DataLoaderIterMultiProcess :228 with worker procs + queues + ParentWatchDog).

TPU-native design: workers produce host numpy batches (multiprocessing); device transfer
happens in the consuming step function (jax device_put is async). The shared-memory
LoDTensor queue of the reference is unnecessary — numpy pickling over a
multiprocessing.Queue feeds a single TPU host fine; jax arrays never cross processes.
"""
import atexit
import itertools
import multiprocessing as mp
import queue as pyqueue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._data) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    return batch


def _np_collate(batch):
    """Collate to plain numpy (used inside worker processes — no jax there)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return [_np_collate([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    return batch


def _to_tensor(obj):
    if isinstance(obj, list):
        return [_to_tensor(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _to_tensor(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    return obj


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id, num_workers, seed):
    np.random.seed(seed + worker_id)
    _worker_info.info = WorkerInfo(worker_id, num_workers, dataset)
    if isinstance(dataset, IterableDataset):
        it = iter(dataset)
        while True:
            msg = index_queue.get()
            if msg is None:
                break
            batch_id, batch_size = msg
            samples = list(itertools.islice(it, batch_size))
            if not samples:
                data_queue.put((batch_id, None))
                break
            data_queue.put((batch_id, collate_fn(samples)))
    else:
        while True:
            msg = index_queue.get()
            if msg is None:
                break
            batch_id, indices = msg
            try:
                samples = [dataset[i] for i in indices]
                data_queue.put((batch_id, collate_fn(samples)))
            except Exception as e:  # surface worker errors to parent
                data_queue.put((batch_id, e))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=False, timeout=120,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.num_workers = num_workers
        self.timeout = timeout
        self._is_iterable_ds = isinstance(dataset, IterableDataset)
        self.collate_fn = collate_fn or (default_collate_fn if num_workers == 0 else _np_collate)
        self._user_collate = collate_fn is not None
        self.prefetch_factor = prefetch_factor
        if self._is_iterable_ds:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._is_iterable_ds:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self.num_workers == 0:
            return self._single_process_iter()
        return self._multi_process_iter()

    def _single_process_iter(self):
        if self._is_iterable_ds:
            it = iter(self.dataset)
            while True:
                samples = list(itertools.islice(it, self.batch_size))
                if not samples or (self.drop_last and len(samples) < self.batch_size):
                    return
                yield self.collate_fn(samples)
        else:
            for indices in self.batch_sampler:
                samples = [self.dataset[i] for i in indices]
                yield self.collate_fn(samples)

    def _multi_process_iter(self):
        ctx = mp.get_context("fork")
        index_queues = []
        data_queue = ctx.Queue()
        workers = []
        seed = np.random.randint(0, 2**31 - 1)
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            w = ctx.Process(
                target=_worker_loop,
                args=(self.dataset, iq, data_queue, self.collate_fn, wid, self.num_workers, seed),
                daemon=True,
            )
            w.start()
            index_queues.append(iq)
            workers.append(w)

        def shutdown():
            for iq in index_queues:
                try:
                    iq.put(None)
                except Exception:
                    pass
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()

        atexit.register(shutdown)
        try:
            if self._is_iterable_ds:
                yield from self._iter_iterable_mp(index_queues, data_queue, workers)
            else:
                yield from self._iter_map_mp(index_queues, data_queue, workers)
        finally:
            shutdown()
            atexit.unregister(shutdown)

    def _iter_map_mp(self, index_queues, data_queue, workers):
        sampler_iter = iter(self.batch_sampler)
        sent = 0
        received = 0
        buffers = {}
        # prime the pipeline
        for _ in range(self.num_workers * self.prefetch_factor):
            try:
                indices = next(sampler_iter)
            except StopIteration:
                break
            index_queues[sent % self.num_workers].put((sent, indices))
            sent += 1
        while received < sent:
            while received in buffers:
                data = buffers.pop(received)
                received += 1
                yield self._finalize(data)
                try:
                    indices = next(sampler_iter)
                    index_queues[sent % self.num_workers].put((sent, indices))
                    sent += 1
                except StopIteration:
                    pass
            if received >= sent:
                break
            # ParentWatchDog (dataloader_iter.py:384): detect dead workers
            if not any(w.is_alive() for w in workers) and data_queue.empty():
                raise RuntimeError("DataLoader workers exited unexpectedly")
            try:
                batch_id, data = data_queue.get(timeout=self.timeout)
            except pyqueue.Empty:
                raise RuntimeError(f"DataLoader timed out after {self.timeout}s")
            if isinstance(data, Exception):
                raise data
            buffers[batch_id] = data

    def _iter_iterable_mp(self, index_queues, data_queue, workers):
        # iterable datasets: each worker holds its own iterator (sharded by worker_info)
        sent = 0
        finished = set()
        for wid in range(self.num_workers):
            index_queues[wid].put((sent, self.batch_size))
            sent += 1
        while len(finished) < self.num_workers:
            batch_id, data = data_queue.get(timeout=self.timeout)
            wid = batch_id % self.num_workers
            if isinstance(data, Exception):
                raise data
            if data is None:
                finished.add(wid)
                continue
            yield self._finalize(data)
            index_queues[wid].put((sent, self.batch_size))
            sent += 1

    def _finalize(self, data):
        if self._user_collate:
            return data
        return _to_tensor(data)

    @staticmethod
    def from_generator(feed_list=None, capacity=None, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        """fluid-era DataLoader.from_generator compatibility shim."""

        class _GenLoader:
            def __init__(self):
                self._gen = None

            def set_sample_generator(self, reader, batch_size, drop_last=True, places=None):
                def batched():
                    batch = []
                    for s in reader():
                        batch.append(s if isinstance(s, (list, tuple)) else (s,))
                        if len(batch) == batch_size:
                            yield default_collate_fn(batch)
                            batch = []
                    if batch and not drop_last:
                        yield default_collate_fn(batch)

                self._gen = batched
                return self

            def set_batch_generator(self, reader, places=None):
                def conv():
                    for b in reader():
                        yield _to_tensor(list(b) if isinstance(b, (list, tuple)) else b)

                self._gen = conv
                return self

            def __iter__(self):
                return iter(self._gen())

        return _GenLoader()
