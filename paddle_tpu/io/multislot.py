"""MultiSlot Dataset API over the native C++ parser.

Reference parity: python/paddle/fluid/dataset.py (InMemoryDataset/QueueDataset) +
framework/data_feed.cc MultiSlot parsing + data_set.cc shuffle — the PS-era dataset
path (Executor.train_from_dataset feeds from these).

TPU-native design: the C++ parser (native/multislot_parser.cc, built on first use with
the system toolchain) produces ragged host buffers; `batch_iter` pads each slot to the
batch max length (+mask) — LoD exists only at this boundary.
"""
import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(__file__), "..", "native", "multislot_parser.cc")
_SO = os.path.join(os.path.dirname(__file__), "..", "native", "_multislot_parser.so")


def _load_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.abspath(_SRC)
        so = os.path.abspath(_SO)
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread", "-o", so, src],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(so)
        lib.msp_create.restype = ctypes.c_void_p
        lib.msp_create.argtypes = [ctypes.POINTER(ctypes.c_int), ctypes.c_int]
        lib.msp_destroy.argtypes = [ctypes.c_void_p]
        lib.msp_clear.argtypes = [ctypes.c_void_p]
        lib.msp_parse_file.restype = ctypes.c_int64
        lib.msp_parse_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.msp_parse_buffer.restype = ctypes.c_int64
        lib.msp_parse_buffer.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.msp_num_instances.restype = ctypes.c_int64
        lib.msp_num_instances.argtypes = [ctypes.c_void_p]
        lib.msp_slot_total_values.restype = ctypes.c_int64
        lib.msp_slot_total_values.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.msp_copy_slot_f.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_float),
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.msp_copy_slot_i.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int64),
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.msp_shuffle.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _LIB = lib
        return lib


class InMemoryDataset:
    """fluid.InMemoryDataset parity: set_use_var-style slot schema, load files into
    the native store, local_shuffle, then iterate padded batches."""

    def __init__(self):
        self._slot_names = []
        self._slot_types = []  # "float32" | "int64"
        self._batch_size = 1
        self._handle = None
        self._filelist = []
        self._thread_num = max(1, (os.cpu_count() or 2) - 1)

    def init(self, batch_size=1, use_var=None, **kwargs):
        self._batch_size = batch_size
        if use_var:
            for v in use_var:
                name = getattr(v, "name", None) or str(v)
                dtype = str(getattr(v, "dtype", "float32"))
                self.add_slot(name, "int64" if "int" in dtype else "float32")
        return self

    def add_slot(self, name, dtype="float32"):
        self._slot_names.append(name)
        self._slot_types.append(dtype)
        return self

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _ensure_handle(self):
        if self._handle is None:
            lib = _load_lib()
            types = (ctypes.c_int * len(self._slot_types))(
                *[0 if t.startswith("float") else 1 for t in self._slot_types]
            )
            self._handle = lib.msp_create(types, len(self._slot_types))
        return _load_lib()

    def load_into_memory(self):
        lib = self._ensure_handle()
        total = 0
        for f in self._filelist:
            n = lib.msp_parse_file(self._handle, f.encode(), self._thread_num)
            if n < 0:
                raise IOError(f"cannot read {f}")
            total += n
        return total

    def load_from_string(self, text):
        lib = self._ensure_handle()
        data = text.encode()
        return lib.msp_parse_buffer(self._handle, data, len(data))

    def local_shuffle(self, seed=0):
        lib = self._ensure_handle()
        lib.msp_shuffle(self._handle, seed)

    def _slots_with_offsets(self):
        """(slots, n, per-slot instance offsets) — shared ragged layout of
        batch_iter and _instance_lines."""
        slots = self._slot_arrays()
        n = self.get_memory_data_size()
        offsets = [np.concatenate([[0], np.cumsum(lens)]) for _, lens in slots]
        return slots, n, offsets

    def _instance_lines(self):
        """Serialize the in-memory instances back to MultiSlot text lines
        (`<count> v v ...` per slot) — the exchange format of global_shuffle.
        float32 values use numpy's shortest float32 repr (strtof round-trips
        it bit-exactly; float() would widen to float64 and ~triple the
        payload)."""
        slots, n, offsets = self._slots_with_offsets()
        # vectorized formatting: %.9g round-trips float32 exactly through
        # strtof; per-value python str() would make the PS-scale exchange
        # O(total values) in interpreted code
        slot_strs = []
        for vals, _ in slots:
            fmt = "%.9g" if vals.dtype == np.float32 else "%d"
            slot_strs.append(np.char.mod(fmt, vals))
        lines = []
        for inst in range(n):
            parts = []
            for (vals, lens), offs, strs in zip(slots, offsets, slot_strs):
                l = int(lens[inst])
                parts.append(str(l))
                parts.extend(strs[offs[inst]:offs[inst] + l])
            lines.append(" ".join(parts))
        return lines

    def global_shuffle(self, fleet=None, thread_num=12, client=None,
                       worker_id=None, worker_num=None, seed=0):
        """Cross-worker instance exchange (data_set.cc Dataset::GlobalShuffle
        parity): every instance is routed to a random worker THROUGH the PS
        servers (shuffle_put/shuffle_get RPC), then locally shuffled. Must be
        called on ALL workers (it rendezvouses at the worker barrier).

        Single-process (no PS client / world 1): plain local shuffle."""
        if client is None and fleet is not None:
            runtime = getattr(fleet, "ps_runtime", None) or getattr(
                getattr(fleet, "fleet", None), "ps_runtime", None)
            client = getattr(runtime, "client", None)
            if worker_id is None and hasattr(fleet, "worker_index"):
                worker_id = fleet.worker_index()
            if worker_num is None and hasattr(fleet, "worker_num"):
                worker_num = fleet.worker_num()
        if worker_id is None:
            worker_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        if worker_num is None:
            worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        if client is None or worker_num <= 1:
            self.local_shuffle(seed)
            return
        lines = self._instance_lines()
        rng = np.random.RandomState(seed + 1000003 * worker_id)
        dsts = rng.randint(0, worker_num, size=len(lines))
        for dst in range(worker_num):
            part = [lines[i] for i in np.flatnonzero(dsts == dst)]
            client.shuffle_put(dst, "\n".join(part))
        # a timed-out barrier means some worker's puts may be missing: getting
        # now would silently drop (and later duplicate) instances — fail loud
        if not client.barrier():
            raise RuntimeError("global_shuffle: worker barrier timed out "
                               "before the exchange completed")
        blobs = client.shuffle_get(worker_id)
        self.release_memory()
        for blob in blobs:
            if blob:
                self.load_from_string(blob + "\n")
        self.local_shuffle(seed + worker_id)
        if not client.barrier():  # all gets done before buffers are reused
            raise RuntimeError("global_shuffle: worker barrier timed out "
                               "after the exchange")

    def get_memory_data_size(self, fleet=None):
        lib = self._ensure_handle()
        return int(lib.msp_num_instances(self._handle))

    def release_memory(self):
        if self._handle is not None:
            _load_lib().msp_clear(self._handle)

    def _slot_arrays(self):
        lib = self._ensure_handle()
        n = self.get_memory_data_size()
        out = []
        for s, t in enumerate(self._slot_types):
            total = lib.msp_slot_total_values(self._handle, s)
            lens = np.zeros(n, dtype=np.int64)
            if t.startswith("float"):
                vals = np.zeros(total, dtype=np.float32)
                lib.msp_copy_slot_f(self._handle, s,
                                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                                    lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            else:
                vals = np.zeros(total, dtype=np.int64)
                lib.msp_copy_slot_i(self._handle, s,
                                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                                    lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            out.append((vals, lens))
        return out

    def batch_iter(self, drop_last=False, return_mask=False):
        """Yield dicts {slot: padded [b, max_len] array (+ '<slot>_mask')}."""
        slots, n, offsets = self._slots_with_offsets()
        bs = self._batch_size
        for b0 in range(0, n, bs):
            b1 = min(n, b0 + bs)
            if b1 - b0 < bs and drop_last:
                break
            batch = {}
            for (vals, lens), offs, name in zip(slots, offsets, self._slot_names):
                ls = lens[b0:b1]
                width = max(1, int(ls.max()) if len(ls) else 1)
                pad = np.zeros((b1 - b0, width), dtype=vals.dtype)
                mask = np.zeros((b1 - b0, width), dtype=np.float32)
                for r, inst in enumerate(range(b0, b1)):
                    l = int(lens[inst])
                    pad[r, :l] = vals[offs[inst] : offs[inst] + l]
                    mask[r, :l] = 1.0
                batch[name] = pad
                if return_mask:
                    batch[name + "_mask"] = mask
            yield batch

    def __del__(self):
        if self._handle is not None:
            try:
                _load_lib().msp_destroy(self._handle)
            except Exception:
                pass


class QueueDataset(InMemoryDataset):
    """fluid.QueueDataset parity — streaming variant; here: parse-on-iterate."""

    def batch_iter(self, drop_last=False, return_mask=False):
        if self.get_memory_data_size() == 0 and self._filelist:
            self.load_into_memory()
        yield from super().batch_iter(drop_last, return_mask)
