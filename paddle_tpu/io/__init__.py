"""paddle.io parity (python/paddle/io/__init__.py)."""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    RandomSplit,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, default_collate_fn, get_worker_info  # noqa: F401
