"""Pass registry + report types for jaxpr analysis.

The shape mirrors the reference's REGISTER_PASS(name, pass) macro
(paddle/fluid/framework/ir/pass.h): passes register under a unique name
with a default severity; `run_passes` traces (or accepts) a jaxpr, runs
every registered pass over one shared AnalysisContext, and assembles an
AnalysisReport whose findings carry pass name / severity / eqn provenance.
"""

# severity ordering is part of the public contract (report sorting and the
# tier-1 gate's "zero errors" criterion both key off it)
SEVERITIES = ("error", "warning", "info")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


class Finding:
    """One analysis result: what pass fired, how bad, and where.

    `where` is provenance — an eqn path like ``eqns[12]/pjit:_bernoulli``
    for jaxpr passes, or ``file.py:123`` for source-lint rules.
    """

    __slots__ = ("pass_name", "severity", "message", "where")

    def __init__(self, pass_name, severity, message, where=""):
        if severity not in _SEV_RANK:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {severity!r}")
        self.pass_name = pass_name
        self.severity = severity
        self.message = message
        self.where = where

    def to_dict(self):
        return {"pass": self.pass_name, "severity": self.severity,
                "message": self.message, "where": self.where}

    def __repr__(self):
        loc = f" @ {self.where}" if self.where else ""
        return f"[{self.severity}] {self.pass_name}: {self.message}{loc}"


class AnalysisReport:
    """Findings for one analyzed target, ordered most-severe first.

    Ordering is STABLE: severity rank, then pass registration order, then
    discovery order — so reports diff cleanly across runs (the baseline
    fixture in tests/lint_baseline.json relies on this).
    """

    def __init__(self, name="", findings=None):
        self.name = name
        self.findings = list(findings or [])

    def add(self, finding):
        self.findings.append(finding)

    def extend(self, findings):
        self.findings.extend(findings)

    def sort(self):
        order = {n: i for i, n in enumerate(registered_passes())}
        self.findings.sort(key=lambda f: (
            _SEV_RANK.get(f.severity, len(SEVERITIES)),
            order.get(f.pass_name, len(order)), f.where))
        return self

    def by_severity(self, severity):
        return [f for f in self.findings if f.severity == severity]

    @property
    def errors(self):
        return self.by_severity("error")

    @property
    def warnings(self):
        return self.by_severity("warning")

    def counts(self):
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def to_dict(self):
        return {"name": self.name, "counts": self.counts(),
                "findings": [f.to_dict() for f in self.sort().findings]}

    def summary(self):
        c = self.counts()
        head = (f"{self.name or 'report'}: {c['error']} error(s), "
                f"{c['warning']} warning(s), {c['info']} info")
        lines = [head] + [f"  {f!r}" for f in self.sort().findings]
        return "\n".join(lines)


class AnalysisContext:
    """Everything a pass may inspect. Passes must treat it as read-only.

    closed_jaxpr : jax ClosedJaxpr of the analyzed function
    name         : label for the report
    mesh         : optional jax Mesh the function is meant to run under
                   (enables the sharding-flow passes)
    donated      : optional frozenset of invar indices already donated
                   (None = donation intent unknown; the donation pass
                   reports at info severity then)
    hlo_text     : optional compiled HLO text (enables the exact-count
                   collective audit on top of the jaxpr-level counts)
    large_threshold : element count above which a tensor is "large"
    in_specs     : optional per-invar shardings (NamedSharding /
                   PartitionSpec / None), seeding the sharding-flow
                   propagation when the trace itself carries none
    """

    def __init__(self, closed_jaxpr, name="", mesh=None, donated=None,
                 hlo_text=None, large_threshold=1 << 20, in_specs=None):
        self.closed_jaxpr = closed_jaxpr
        self.jaxpr = closed_jaxpr.jaxpr
        self.consts = list(closed_jaxpr.consts)
        self.name = name
        self.mesh = mesh
        self.donated = donated if donated is None else frozenset(donated)
        self.hlo_text = hlo_text
        self.large_threshold = int(large_threshold)
        self.in_specs = None if in_specs is None else tuple(in_specs)


_PASSES = {}        # name -> (fn, default_severity)
_PASS_ORDER = []    # registration order (stable report ordering)


def register_pass(name, severity="warning"):
    """Decorator: register fn(ctx) -> iterable[Finding] under `name`.

    Duplicate names are rejected (same contract as the reference's
    PassRegistry::Insert CHECK). `severity` is the pass's default for
    findings built via the injected `finding(...)` convenience attribute.
    """
    if severity not in _SEV_RANK:
        raise ValueError(
            f"severity must be one of {SEVERITIES}, got {severity!r}")

    def deco(fn):
        if name in _PASSES:
            raise ValueError(f"analysis pass {name!r} already registered")
        def finding(message, where="", severity=severity):
            return Finding(name, severity, message, where)
        fn.finding = finding
        fn.pass_name = name
        fn.default_severity = severity
        _PASSES[name] = (fn, severity)
        _PASS_ORDER.append(name)
        return fn

    return deco


def registered_passes():
    """Pass names in registration order."""
    return list(_PASS_ORDER)


def _as_closed_jaxpr(fn_or_jaxpr, args, kwargs):
    import jax

    if isinstance(fn_or_jaxpr, jax.core.ClosedJaxpr):
        return fn_or_jaxpr
    if isinstance(fn_or_jaxpr, jax.core.Jaxpr):
        return jax.core.ClosedJaxpr(fn_or_jaxpr, ())
    if callable(fn_or_jaxpr):
        return jax.make_jaxpr(fn_or_jaxpr)(*args, **kwargs)
    raise TypeError(
        "run_passes expects a ClosedJaxpr, a Jaxpr, or a traceable "
        f"callable; got {type(fn_or_jaxpr).__name__} (for a static "
        "Program use Program.analysis_jaxpr(feed), for a Predictor use "
        "Predictor.analysis_jaxpr())")


def run_passes(fn_or_jaxpr, *args, passes=None, name=None, mesh=None,
               donated=None, hlo_text=None, large_threshold=1 << 20,
               in_specs=None, **kwargs):
    """Run (a subset of) the registered passes; returns an AnalysisReport.

    fn_or_jaxpr: a jax ClosedJaxpr/Jaxpr, or a callable traced with *args
    via jax.make_jaxpr (tracing only — nothing is compiled or executed).
    passes: optional iterable of pass names to run (default: all).
    """
    closed = _as_closed_jaxpr(fn_or_jaxpr, args, kwargs)
    label = name or getattr(fn_or_jaxpr, "__name__", "") or "jaxpr"
    ctx = AnalysisContext(closed, name=label, mesh=mesh, donated=donated,
                          hlo_text=hlo_text, large_threshold=large_threshold,
                          in_specs=in_specs)
    selected = list(_PASS_ORDER) if passes is None else list(passes)
    unknown = [p for p in selected if p not in _PASSES]
    if unknown:
        raise ValueError(f"unknown analysis pass(es) {unknown}; "
                         f"registered: {registered_passes()}")
    report = AnalysisReport(name=label)
    for pname in selected:
        fn, _ = _PASSES[pname]
        report.extend(fn(ctx) or ())
    return report.sort()
