"""Sharding-flow analysis: partition specs propagated through jaxprs.

The distributed layer's invariants lived in reviewer heads until ISSUE 13:
a missing sharding constraint replicates a tensor on every device, a typo'd
collective axis deadlocks (or worse, silently runs on the wrong group), a
non-bijective ppermute drops a rank's activation on the floor, and a
collective inside one cond arm but not the other is a rank-divergence
deadlock the 900s TPU watchdog reports as "timeout". All of it is visible
statically — this module propagates NamedSharding/PartitionSpec facts
through a traced program's jaxpr under the mesh it is meant to run on and
turns each hazard into a Finding with the offending provenance chain.

Passes (registered in the ordinary pass registry, so they ride every
``run_passes`` call; all are inert without the relevant structure):

- ``implicit-replication`` (warning): a large intermediate whose value is
  MATERIALIZED replicated inside the graph — built from iota/broadcast/
  trace constants that no declared sharding covers — under a multi-device
  mesh. Declared-replicated *inputs* (dp params, optimizer moments) are
  intentional and everything derived from them inherits that intent; what
  this pass hunts is replication nobody declared. Upgrades PR 1's
  size-threshold-only ``unsharded-large-tensor`` pass: findings carry the
  provenance chain from the offending value back to its origin.
- ``resharding-churn`` (warning): a value constrained to spec S1 is
  re-constrained to a different S2 (same shape) — the partitioner lowers
  that as all-gather + re-slice every step.
- ``collective-axis-mismatch`` (error): a psum/ppermute/all_to_all/
  all_gather/axis_index names an axis no enclosing shard_map binds, or an
  axis absent from (or sized differently than) the deployment mesh.
- ``ppermute-malformed`` (error): a ppermute whose permutation is not a
  bijection, contains self-referential (i, i) pairs, or indexes outside
  the axis size.
- ``branch-collective-mismatch`` (error): cond branch arms with different
  collective sequences — ranks disagreeing on the predicate deadlock in
  the arm's collective (while-loop *predicates* containing collectives
  warn under the same pass).

Targets: ``sharding_reports()`` traces the bundled distributed programs
under their real meshes — gpt/bert/ernie SpmdTrainer steps (dp), the dp8
quantized-allreduce step (shard_map + int8 exchange), the pipeline
trainer (pp, ppermute ring), the serving decode step, and the
disaggregated prefill program — and runs the full battery over each.
CLI: ``python tools/graph_lint.py --sharding`` (folded into ``--all``);
tier-1: tests/test_sharding_gate.py. See docs/ANALYSIS.md.
"""
import numpy as np

from .jaxpr_utils import fmt_aval, iter_eqns, sub_jaxprs
from .registry import register_pass

#: rule -> severity, merged into the --list-rules vocabulary on both CLIs
RULES = {
    "implicit-replication": "warning",
    "resharding-churn": "warning",
    "collective-axis-mismatch": "error",
    "ppermute-malformed": "error",
    "branch-collective-mismatch": "error",
}

# jaxpr spellings of the named-axis collectives (psum traces as psum2 on
# current jax; reduce_scatter is psum_scatter's primitive name)
REDUCE_PRIMS = {"psum", "psum2", "pmin", "pmax", "pmin2", "pmax2"}
EXCHANGE_PRIMS = {"all_gather", "all_to_all", "psum_scatter",
                  "reduce_scatter", "pgather"}
PERMUTE_PRIMS = {"ppermute", "pshuffle"}
COLLECTIVE_PRIMS = REDUCE_PRIMS | EXCHANGE_PRIMS | PERMUTE_PRIMS
#: axis-consuming but not collective-sequenced (no wire traffic to match)
AXIS_ONLY_PRIMS = {"axis_index", "pvary", "pbroadcast", "pcast"}


def _axes_of(eqn):
    """Named axes an eqn consumes, normalized to a tuple of strings
    (positional/vmap integer axes are not deployment-mesh axes)."""
    raw = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if raw is None:
        return ()
    if not isinstance(raw, (tuple, list, frozenset, set)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def check_permutation(perm, axis_size=None):
    """Problems with a ppermute permutation: returns a list of strings
    (empty = proven bijective, non-self-referential, in range). A
    size-1 axis is exempt: its only possible permutation is the
    identity no-op a degenerate (single-device) mesh legitimately
    traces."""
    if axis_size == 1:
        return [f"rank(s) {sorted({r for p in perm for r in p if r})} "
                "outside the axis size 1"] if any(
                    r for p in perm for r in p) else []
    problems = []
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
    dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
    if dup_src:
        problems.append(f"duplicate source rank(s) {dup_src} — one rank "
                        "sends twice, not a permutation")
    if dup_dst:
        problems.append(f"duplicate destination rank(s) {dup_dst} — two "
                        "ranks send to one, not a bijection")
    selfs = sorted({s for s, d in perm if s == d})
    if selfs:
        problems.append(f"self-referential pair(s) {[(s, s) for s in selfs]}"
                        " — a rank permuting to itself is a wire no-op that"
                        " still pays the collective")
    if axis_size is not None:
        oob = sorted({r for p in perm for r in p
                      if not 0 <= r < axis_size})
        if oob:
            problems.append(f"rank(s) {oob} outside the axis size "
                            f"{axis_size}")
    return problems


# ---------------------------------------------------------------------------
# axis-environment walk: every eqn with the manual axes bound around it
# ---------------------------------------------------------------------------


def _shard_map_axes(eqn):
    """(manual axis names, mesh) bound by a shard_map eqn."""
    mesh = eqn.params.get("mesh")
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    auto = eqn.params.get("auto") or ()
    return tuple(n for n in names if n not in auto), mesh


def _iter_with_axes(jaxpr, path="", axes_env=(), sm_mesh=None, depth=32):
    """Depth-first (eqn, path, axes_env, sm_mesh): like iter_eqns but
    threading the enclosing shard_map's manual axis names and mesh."""
    if depth < 0:
        return
    for i, eqn in enumerate(jaxpr.eqns):
        here = f"{path}eqns[{i}]"
        yield eqn, here, axes_env, sm_mesh
        tag = eqn.params.get("name", "")
        label = f"{eqn.primitive.name}:{tag}" if tag else eqn.primitive.name
        env, mesh = axes_env, sm_mesh
        if eqn.primitive.name == "shard_map":
            bound, m = _shard_map_axes(eqn)
            env = tuple(dict.fromkeys(axes_env + bound))
            mesh = m or sm_mesh
        for _, sub in sub_jaxprs(eqn):
            inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            yield from _iter_with_axes(inner, f"{here}/{label}/", env,
                                       mesh, depth - 1)


def _axis_size(axis, sm_mesh, ctx_mesh):
    for mesh in (sm_mesh, ctx_mesh):
        shape = getattr(mesh, "shape", None)
        if shape and axis in shape:
            return shape[axis]
    return None


# ---------------------------------------------------------------------------
# collective soundness
# ---------------------------------------------------------------------------


@register_pass("collective-axis-mismatch", severity="error")
def collective_axis_mismatch(ctx):
    """Every collective's axis names must be bound by an enclosing
    shard_map AND exist (same size) on the deployment mesh."""
    out = []
    mesh_axes = tuple(getattr(ctx.mesh, "axis_names", ()) or ()) \
        if ctx.mesh is not None else None
    for eqn, path, env, sm_mesh in _iter_with_axes(ctx.jaxpr):
        p = eqn.primitive.name
        if p == "shard_map" and ctx.mesh is not None:
            for a in _shard_map_axes(eqn)[0]:
                if a not in mesh_axes:
                    out.append(collective_axis_mismatch.finding(
                        f"shard_map binds axis '{a}' that the deployment "
                        f"mesh {dict(ctx.mesh.shape)} does not have",
                        where=path))
                elif _axis_size(a, eqn.params.get("mesh"), None) not in (
                        None, ctx.mesh.shape[a]):
                    out.append(collective_axis_mismatch.finding(
                        f"shard_map axis '{a}' has size "
                        f"{eqn.params['mesh'].shape[a]} but the deployment "
                        f"mesh gives it {ctx.mesh.shape[a]}", where=path))
            continue
        if p not in COLLECTIVE_PRIMS and p not in AXIS_ONLY_PRIMS:
            continue
        for a in _axes_of(eqn):
            if a not in env:
                out.append(collective_axis_mismatch.finding(
                    f"'{p}' over axis '{a}' with no enclosing shard_map "
                    f"binding it (bound here: {sorted(env) or 'none'})",
                    where=path))
            elif mesh_axes is not None and a not in mesh_axes:
                out.append(collective_axis_mismatch.finding(
                    f"'{p}' over axis '{a}' absent from the deployment "
                    f"mesh {dict(ctx.mesh.shape)} — the program cannot "
                    "run on the mesh it is analyzed for", where=path))
    return out


@register_pass("ppermute-malformed", severity="error")
def ppermute_malformed(ctx):
    """ppermute permutations proven bijective, non-self-referential, and
    in-range for the axis size."""
    out = []
    for eqn, path, env, sm_mesh in _iter_with_axes(ctx.jaxpr):
        if eqn.primitive.name not in PERMUTE_PRIMS:
            continue
        perm = tuple(tuple(p) for p in eqn.params.get("perm", ()))
        axes = _axes_of(eqn)
        size = _axis_size(axes[0], sm_mesh, ctx.mesh) if axes else None
        for problem in check_permutation(perm, axis_size=size):
            out.append(ppermute_malformed.finding(
                f"ppermute over {axes or '?'} perm={list(perm)}: "
                f"{problem}", where=path))
    return out


def _collective_sequence(jaxpr, depth=32):
    """Ordered (primitive-family, axes) sequence of every collective at
    every nesting depth — the wire program two branch arms must agree on."""
    seq = []
    for eqn, _ in iter_eqns(jaxpr, max_depth=depth):
        p = eqn.primitive.name
        if p in COLLECTIVE_PRIMS:
            fam = ("reduce" if p in REDUCE_PRIMS
                   else "permute" if p in PERMUTE_PRIMS else p)
            seq.append((fam, _axes_of(eqn)))
    return tuple(seq)


@register_pass("branch-collective-mismatch", severity="error")
def branch_collective_mismatch(ctx):
    """cond arms must issue identical collective sequences (all ranks take
    the arm their own predicate picks — divergent predicates leave some
    ranks waiting in a collective the others never enter). while-loop
    PREDICATES containing collectives warn: a rank-varying trip count is
    the same deadlock one level up."""
    out = []
    for eqn, path, env, _ in _iter_with_axes(ctx.jaxpr):
        p = eqn.primitive.name
        if p == "cond":
            branches = eqn.params.get("branches", ())
            seqs = []
            for b in branches:
                inner = b.jaxpr if hasattr(b, "jaxpr") else b
                seqs.append(_collective_sequence(inner))
            if len(set(seqs)) > 1:
                desc = "; ".join(
                    f"arm[{i}]: {[f'{f}{list(a)}' for f, a in s] or 'none'}"
                    for i, s in enumerate(seqs))
                out.append(branch_collective_mismatch.finding(
                    "cond arms issue different collective sequences — a "
                    "rank-divergent predicate deadlocks the arm with the "
                    f"extra collective ({desc})", where=path))
        elif p == "while":
            cond_j = eqn.params.get("cond_jaxpr")
            if cond_j is not None:
                inner = cond_j.jaxpr if hasattr(cond_j, "jaxpr") else cond_j
                seq = _collective_sequence(inner)
                if seq:
                    out.append(branch_collective_mismatch.finding(
                        f"while-loop predicate contains collectives "
                        f"({[f'{f}{list(a)}' for f, a in seq]}) — a rank-"
                        "varying trip count hangs the slower ranks",
                        where=path, severity="warning"))
    return out


# ---------------------------------------------------------------------------
# partition-spec propagation (implicit replication + resharding churn)
# ---------------------------------------------------------------------------

_UNKNOWN = "unknown"     # no sharding information
_SHARDED = "sharded"     # derived from sharded data, exact spec unknown


class _Spec:
    """A known placement: a PartitionSpec-like tuple plus where it came
    from ('declared' input/constraint vs 'derived' propagation)."""

    __slots__ = ("dims", "declared")

    def __init__(self, dims, declared=False):
        self.dims = tuple(dims)
        self.declared = declared

    @property
    def replicated(self):
        return all(d is None for d in self.dims)

    def __repr__(self):
        inner = ", ".join("None" if d is None else repr(d)
                          for d in self.dims)
        return f"P({inner})"


def _norm_spec(spec_like, rank, declared=False):
    """NamedSharding / PartitionSpec / dim-dict -> _Spec of `rank`."""
    spec = getattr(spec_like, "spec", spec_like)
    if isinstance(spec_like, dict):   # shard_map in_names/out_names form
        dims = [None] * rank
        for d, names in spec_like.items():
            if int(d) < rank:
                dims[int(d)] = tuple(names) if names else None
        return _Spec(dims, declared)
    try:
        entries = tuple(spec)
    except TypeError:
        return None
    dims = []
    for e in entries[:rank]:
        if e is None:
            dims.append(None)
        elif isinstance(e, (tuple, list)):
            dims.append(tuple(e))
        else:
            dims.append((str(e),))
    dims += [None] * (rank - len(dims))
    return _Spec(dims, declared)


def _is_named_sharding(obj):
    return hasattr(obj, "spec") and hasattr(obj, "mesh")


def _rank(var):
    shape = getattr(getattr(var, "aval", None), "shape", None)
    return None if shape is None else len(shape)


def _size(var):
    shape = getattr(getattr(var, "aval", None), "shape", None)
    if not shape:
        return 0
    try:
        return int(np.prod(shape))
    except Exception:
        return 0


#: primitives that taint instead of propagate (output layout is not the
#: input layout) — anything not listed and not shape-preserving also taints
_REDUCE_SHAPED = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
                  "reduce_and", "reduce_or", "argmax", "argmin"}
_MATERIALIZERS = {"iota", "broadcast_in_dim"}


class _SpecFlow:
    """One propagation over a (possibly pjit-nested) jaxpr.

    env maps id(var) -> _Spec | 'sharded' | 'unknown'. origin maps
    id(var) -> (label, path, parent_id|None) so replication findings can
    print the chain from the offender back to the value that introduced
    the replication.
    """

    def __init__(self, large_threshold):
        self.large_threshold = large_threshold
        self.env = {}
        self.origin = {}
        self.constrained = set()          # ids consumed by a constraint
        self.replicated_offenders = []    # (path, var, root_kind)
        self.churn = []                   # (path, old_spec, new_spec, var)

    # -- provenance ---------------------------------------------------------
    def _note(self, var, label, path, parent=None):
        vid = id(var)
        if vid not in self.origin:
            self.origin[vid] = (label, path,
                                None if parent is None else id(parent))

    def chain(self, var, max_hops=8):
        """Human-readable provenance chain for a var."""
        parts = []
        vid = id(var)
        for _ in range(max_hops):
            entry = self.origin.get(vid)
            if entry is None:
                break
            label, path, parent = entry
            parts.append(f"{label}" + (f" @ {path}" if path else ""))
            if parent is None:
                break
            vid = parent
        return " <- ".join(parts) if parts else "(origin unknown)"

    # -- env helpers --------------------------------------------------------
    def get(self, var):
        from .jaxpr_utils import is_literal

        if is_literal(var):
            return _Spec((), declared=False)   # scalars: neutral
        return self.env.get(id(var), _UNKNOWN)

    def set(self, var, state):
        self.env[id(var)] = state

    # -- propagation --------------------------------------------------------
    def run(self, jaxpr, in_states=None, path=""):
        """Propagate through `jaxpr`; in_states aligns with jaxpr.invars
        (missing entries default to unknown). Returns outvar states."""
        from .jaxpr_utils import is_literal

        if in_states:
            for var, st in zip(jaxpr.invars, in_states):
                if st is not None:
                    self.set(var, st)
        for i, var in enumerate(jaxpr.invars):
            self._note(var, self._invar_label(var, i), path)
        for i, var in enumerate(jaxpr.constvars):
            self.set(var, _Spec((None,) * (_rank(var) or 0)))
            self._note(var, f"constvar[{i}] {fmt_aval(var.aval)} (baked "
                            "trace constant, replicated)", path)

        for i, eqn in enumerate(jaxpr.eqns):
            here = f"{path}eqns[{i}]"
            self._eqn(eqn, here)
        return [self.get(v) if not is_literal(v) else _Spec(())
                for v in jaxpr.outvars]

    def _invar_label(self, var, i):
        st = self.env.get(id(var))
        if isinstance(st, _Spec) and st.declared:
            return f"invar[{i}] {fmt_aval(var.aval)} declared {st!r}"
        return f"invar[{i}] {fmt_aval(var.aval)}"

    def _join(self, states):
        """Combine same-shape operand states: any sharded wins, agreeing
        specs pass through, disagreement degrades to sharded-unknown."""
        specs = [s for s in states if isinstance(s, _Spec)]
        if any(s is _SHARDED for s in states):
            return _SHARDED
        non_repl = [s for s in specs if not s.replicated]
        if non_repl:
            dims = non_repl[0].dims
            return (non_repl[0] if all(s.dims == dims for s in non_repl)
                    else _SHARDED)
        if specs and len(specs) == len(states):
            return _Spec(specs[0].dims)
        return _UNKNOWN

    def _eqn(self, eqn, here):
        p = eqn.primitive.name
        invars = [v for v in eqn.invars]
        in_states = [self.get(v) for v in invars]

        if p == "sharding_constraint" or p == "with_sharding_constraint":
            new = eqn.params.get("sharding")
            rank = _rank(eqn.outvars[0]) or 0
            spec = (_norm_spec(new, rank, declared=True)
                    if new is not None else None)
            old = in_states[0] if in_states else _UNKNOWN
            if (spec is not None and isinstance(old, _Spec)
                    and not old.replicated and old.dims != spec.dims
                    and _size(eqn.outvars[0]) >= self.large_threshold):
                self.churn.append((here, old, spec, eqn.outvars[0]))
            for v in invars:
                self.constrained.add(id(v))
            for ov in eqn.outvars:
                self.constrained.add(id(ov))
                self.set(ov, spec if spec is not None else old)
                self._note(ov, f"sharding_constraint {spec!r}", here,
                           invars[0] if invars else None)
            return

        if p == "pjit":
            self._pjit(eqn, here, in_states)
            return

        if p == "shard_map":
            # the body is manual — per-shard shapes, explicit collectives;
            # replication analysis restarts at the outputs via out_names
            out_names = eqn.params.get("out_names", ())
            for ov, names in zip(eqn.outvars, out_names):
                rank = _rank(ov) or 0
                self.set(ov, _norm_spec(dict(names), rank, declared=True))
                self._note(ov, f"shard_map out {dict(names)}", here)
            return

        subs = [s for _, s in sub_jaxprs(eqn)]
        if subs:
            # scan/while/cond/custom-vjp bodies: taint rule only
            st = self._join(in_states) if in_states else _UNKNOWN
            for ov in eqn.outvars:
                rank = _rank(ov)
                if isinstance(st, _Spec) and st.replicated \
                        and rank is not None:
                    self.set(ov, _Spec((None,) * rank))
                else:
                    self.set(ov, st if st is _SHARDED else _UNKNOWN)
                self._note(ov, f"{p}", here, invars[0] if invars else None)
                self._maybe_flag(ov, here)
            return

        for ov in eqn.outvars:
            rank = _rank(ov)
            if rank is None:
                continue
            st = self._propagate(p, eqn, invars, in_states, ov)
            self.set(ov, st)
            parent = invars[0] if invars else None
            if p in _MATERIALIZERS and all(
                    not isinstance(s, _Spec) or s.replicated or
                    _size(v) == 0
                    for s, v in zip(in_states, invars)):
                self._note(ov, f"{p} {fmt_aval(ov.aval)} (materialized "
                                "replicated in-graph)", here, None)
            else:
                self._note(ov, p, here, parent)
            self._maybe_flag(ov, here)

    def _propagate(self, p, eqn, invars, in_states, ov):
        rank = _rank(ov)
        out_shape = tuple(ov.aval.shape)
        if p in _MATERIALIZERS:
            if p == "broadcast_in_dim" and invars:
                src = in_states[0]
                if src is _SHARDED:
                    return _SHARDED
                if isinstance(src, _Spec):
                    dims = [None] * rank
                    bdims = eqn.params.get("broadcast_dimensions", ())
                    for sdim, odim in enumerate(bdims):
                        if sdim < len(src.dims):
                            dims[odim] = src.dims[sdim]
                    return _Spec(dims)
                return _UNKNOWN
            return _Spec((None,) * rank)   # iota: replicated by birth
        if p == "transpose":
            src = in_states[0]
            if isinstance(src, _Spec):
                perm = eqn.params.get("permutation", ())
                return _Spec(tuple(src.dims[d] if d < len(src.dims)
                                   else None for d in perm))
            return src
        if p in _REDUCE_SHAPED:
            src = in_states[0]
            if isinstance(src, _Spec):
                axes = set(eqn.params.get("axes", ()))
                return _Spec(tuple(d for i, d in enumerate(src.dims)
                                   if i not in axes))
            return src
        # shape-preserving ops (elementwise, converts, select, ...): join
        same = [s for s, v in zip(in_states, invars)
                if getattr(getattr(v, "aval", None), "shape", None)
                == out_shape]
        if same:
            return self._join(same + [
                s for s, v in zip(in_states, invars)
                if _size(v) <= 1])
        # layout-changing op (dot_general, reshape, gather, concat, ...):
        # replicated-only inputs stay replicated, sharded inputs taint
        if in_states and all(
                isinstance(s, _Spec) and s.replicated for s in in_states):
            return _Spec((None,) * rank)
        if any(s is _SHARDED or (isinstance(s, _Spec) and not s.replicated)
               for s in in_states):
            return _SHARDED
        return _UNKNOWN

    def _pjit(self, eqn, here, in_states):
        inner = eqn.params["jaxpr"]
        inner_jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
        declared = eqn.params.get("in_shardings", ())
        seeds = []
        for k, var in enumerate(inner_jaxpr.invars):
            st = in_states[k] if k < len(in_states) else None
            sh = declared[k] if k < len(declared) else None
            if _is_named_sharding(sh):
                st = _norm_spec(sh, _rank(var) or 0, declared=True)
            seeds.append(st if st not in (_UNKNOWN,) else None)
        tag = eqn.params.get("name", "")
        label = f"pjit:{tag}" if tag else "pjit"
        out_states = self.run(inner_jaxpr, seeds, f"{here}/{label}/")
        out_decl = eqn.params.get("out_shardings", ())
        for k, ov in enumerate(eqn.outvars):
            st = out_states[k] if k < len(out_states) else _UNKNOWN
            sh = out_decl[k] if k < len(out_decl) else None
            if _is_named_sharding(sh):
                st = _norm_spec(sh, _rank(ov) or 0, declared=True)
            self.set(ov, st)
            self._note(ov, label, here,
                       inner_jaxpr.outvars[k]
                       if k < len(inner_jaxpr.outvars) and
                       hasattr(inner_jaxpr.outvars[k], "aval") else None)

    def _maybe_flag(self, ov, here):
        """Record a replication offender: large, provably replicated, and
        rooted at an in-graph materializer/constant (not a declared
        input — dp-replicated params are intentional by declaration)."""
        st = self.env.get(id(ov))
        if not isinstance(st, _Spec) or not st.replicated:
            return
        if _size(ov) < self.large_threshold:
            return
        root = self._root_kind(ov)
        if root is not None:
            self.replicated_offenders.append((here, ov, root))

    def _root_kind(self, var, max_hops=16):
        """'materialized'/'const' when the provenance root is an in-graph
        materializer or baked constant; None when it reaches a declared
        input (intentional replication)."""
        vid = id(var)
        for _ in range(max_hops):
            entry = self.origin.get(vid)
            if entry is None:
                return None
            label, _, parent = entry
            if parent is None:
                if label.startswith("invar["):
                    return None
                if "constvar" in label:
                    return "const"
                if "materialized" in label:
                    return "materialized"
                return None
            vid = parent
        return None


def _mesh_size(mesh):
    try:
        return int(np.prod(list(mesh.shape.values())))
    except Exception:
        return 1


def _flow_for(ctx):
    """One propagation per AnalysisContext, memoized on the ctx object
    (two passes share it)."""
    flow = getattr(ctx, "_sharding_flow", None)
    if flow is None:
        flow = _SpecFlow(ctx.large_threshold)
        seeds = None
        in_specs = getattr(ctx, "in_specs", None)
        if in_specs is not None:
            seeds = [None if s is None else
                     _norm_spec(s, _rank(v) or 0, declared=True)
                     for s, v in zip(in_specs, ctx.jaxpr.invars)]
        flow.run(ctx.jaxpr, seeds)
        ctx._sharding_flow = flow
    return flow


@register_pass("implicit-replication", severity="warning")
def implicit_replication(ctx):
    """Large tensors MATERIALIZED replicated in-graph under a multi-device
    mesh, with the provenance chain to the value that introduced the
    replication. Upgrades the size-threshold-only unsharded-large-tensor
    pass: declared-replicated inputs (and everything derived from sharded
    data) never false-positive."""
    if ctx.mesh is None or _mesh_size(ctx.mesh) <= 1:
        return []
    flow = _flow_for(ctx)
    out = []
    # a later sharding_constraint covers an earlier producer: filter at
    # report time, after the whole walk populated `constrained`
    offenders = [(p, v, r) for p, v, r in flow.replicated_offenders
                 if id(v) not in flow.constrained]
    for path, var, root in offenders[:8]:
        out.append(implicit_replication.finding(
            f"{fmt_aval(var.aval)} ({_size(var)} elems) is materialized "
            f"replicated on every device of the {dict(ctx.mesh.shape)} "
            f"mesh ({'baked trace constant' if root == 'const' else 'built in-graph from iota/broadcast'}, "
            "no declared sharding covers it) — provenance: "
            f"{flow.chain(var)}", where=path))
    extra = len(offenders) - 8
    if extra > 0:
        out.append(implicit_replication.finding(
            f"... and {extra} more implicitly-replicated large "
            "intermediate(s)", where="(summary)"))
    return out


@register_pass("resharding-churn", severity="warning")
def resharding_churn(ctx):
    """A value constrained to one spec then re-constrained to another:
    the partitioner lowers the transition as all-gather + re-slice on
    what is, in every analyzed program, the train/decode hot path."""
    if ctx.mesh is None:
        return []
    flow = _flow_for(ctx)
    out = []
    for path, old, new, var in flow.churn[:8]:
        out.append(resharding_churn.finding(
            f"{fmt_aval(var.aval)} re-constrained {old!r} -> {new!r}: "
            "the spec change implies an all-gather + re-slice every "
            f"step — provenance: {flow.chain(var)}", where=path))
    return out


# ---------------------------------------------------------------------------
# bundled-program targets (tools/graph_lint.py --sharding)
# ---------------------------------------------------------------------------

SHARDING_TARGETS = ("gpt_train", "bert_train", "ernie_train", "serving",
                    "dp8_quantized", "pipeline", "disagg", "mpmd_train")

#: analysis threshold for the bundled CPU-shrunk programs. 1<<17 keeps
#: the CI-size traces quiet (a [16, 4, 16, 16] attention mask is 16k
#: elements — replicated, true, and fused away by XLA at this size)
#: while the same pass at production shapes flags the [b, h, s, s] mask
#: class flash attention exists to avoid. Planted unit tests exercise
#: the machinery with explicit low thresholds.
TARGET_THRESHOLD = 1 << 17


def _tiny_train_setup(model_name, dp):
    import jax

    import paddle_tpu as paddle
    from ..distributed.mesh import build_mesh
    from ..distributed.spmd import SpmdTrainer
    from ..models import (BertConfig, BertForPretraining, BertPretrainLoss,
                          ErnieConfig, ErnieModel, ErniePretrainLoss,
                          GPTConfig, GPTForCausalLM, GPTPretrainLoss)

    dims = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                dropout=0.0)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    b, s = 2 * dp, 16
    if model_name == "gpt":
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **dims))
        loss = GPTPretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    elif model_name == "bert":
        model = BertForPretraining(BertConfig(max_position=64,
                                              intermediate_size=256,
                                              **dims))
        loss = BertPretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 np.zeros((b, s), np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    elif model_name == "ernie":
        class _ErnieWithHead(paddle.nn.Layer):
            """seq output -> MLM logits + pooled NSP head (the pretrain
            program shape; MLM-only labels through the flat batch)."""

            def __init__(self, cfg):
                super().__init__()
                self.ernie = ErnieModel(cfg)
                self.mlm = paddle.nn.Linear(cfg.hidden_size,
                                            cfg.vocab_size)
                self.nsp = paddle.nn.Linear(cfg.hidden_size, 2)

            def forward(self, ids):
                seq, pooled = self.ernie(ids)
                return self.mlm(seq), self.nsp(pooled)

        model = _ErnieWithHead(ErnieConfig(max_position=64,
                                           intermediate_size=256, **dims))
        loss = ErniePretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    else:
        raise ValueError(model_name)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((dp,), ("dp",), devices=jax.devices()[:dp])
    trainer = SpmdTrainer(model, opt, loss_fn=loss, mesh=mesh)
    return trainer, tuple(batch), mesh


def _donated_of(closed):
    """The pjit-declared donation set of a traced jitted program — the
    donation-miss pass's ground truth."""
    donated = set()
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            for i, d in enumerate(eqn.params.get("donated_invars", ())):
                if d:
                    donated.add(i)
    return donated


def _trace_trainer_step(trainer, batch_arrays):
    """ClosedJaxpr of the trainer's jitted step (trace only, no compile),
    plus the pjit-declared donation set for the donation-miss pass."""
    import jax
    import jax.numpy as jnp

    from ..core.generator import default_generator

    step = trainer._build(list(batch_arrays))
    lr = jnp.asarray(trainer.optimizer.get_lr(), dtype=jnp.float32)
    key = default_generator().fold_in(0)
    closed = jax.make_jaxpr(step)(trainer.params, trainer.opt_state,
                                  trainer.buffers, lr, key, *batch_arrays)
    return closed, _donated_of(closed)


def _dp(n_want):
    import jax

    return max(1, min(n_want, len(jax.devices())))


def _target_train(model_name):
    trainer, batch, mesh = _tiny_train_setup(model_name, _dp(8))
    closed, donated = _trace_trainer_step(trainer, batch)
    return closed, dict(mesh=mesh, donated=donated)


def _target_dp8_quantized():
    from .. import flags as _flags

    old = {"quantized_allreduce": _flags.get_flag("quantized_allreduce",
                                                  False)}
    _flags.set_flags({"quantized_allreduce": True})
    try:
        trainer, batch, mesh = _tiny_train_setup("gpt", _dp(8))
        closed, donated = _trace_trainer_step(trainer, batch)
    finally:
        _flags.set_flags(old)
    return closed, dict(mesh=mesh, donated=donated)


def _target_pipeline():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..distributed.mesh import build_mesh
    from ..distributed.pipeline import PipelineTrainer
    from ..models import GPTConfig, GPTForCausalLM

    n_pp = _dp(4)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=n_pp,
                    num_heads=4, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    pre, stages, post = model.pipeline_split(n_pp)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh((n_pp,), ("pp",), devices=jax.devices()[:n_pp])
    tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                         n_micro=n_pp, schedule_mode="F-then-B")
    rng = np.random.RandomState(0)
    b, s = n_pp * 2, 16
    x = rng.randint(0, 256, (b, s)).astype(np.int32)
    y = rng.randint(0, 256, (b, s)).astype(np.int32)
    mb = b // tr.n_micro
    x_micro = jnp.asarray(x).reshape((tr.n_micro, mb, s))
    y_micro = jnp.asarray(y).reshape((tr.n_micro, mb, s))
    step = tr._build()
    lr = jnp.asarray(tr.optimizer.get_lr(), dtype=jnp.float32)
    closed = jax.make_jaxpr(step)(tr.params, tr.opt_state, tr.frozen, lr,
                                  x_micro, y_micro)
    return closed, dict(mesh=mesh, donated=_donated_of(closed))


def _target_mpmd():
    """The FLAGS_mpmd armed pipeline (distributed/stage.py): per-stage
    programs on their own mesh slices. The traced program is the fused
    last stage (loss + grads — the densest of the per-stage programs);
    its mesh is that stage's OWN mesh, not the trainer's."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from .. import flags as _flags
    from ..distributed.mesh import build_mesh
    from ..distributed.pipeline import PipelineTrainer
    from ..models import GPTConfig, GPTForCausalLM

    n_pp = max(2, _dp(2))
    old = {"mpmd": _flags.get_flag("mpmd", False)}
    _flags.set_flags({"mpmd": True})
    try:
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=n_pp,
                        num_heads=4, max_seq_len=64, dropout=0.0)
        model = GPTForCausalLM(cfg)
        pre, stages, post = model.pipeline_split(n_pp)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        mesh = build_mesh((n_pp,), ("pp",), devices=jax.devices()[:n_pp])
        tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                             n_micro=n_pp, schedule_mode="F-then-B")
        rng = np.random.RandomState(0)
        b, s = n_pp * 2, 16
        mb = b // tr.n_micro
        x_micro = jnp.asarray(
            rng.randint(0, 256, (b, s)).astype(np.int32)).reshape(
                (tr.n_micro, mb, s))
        y_micro = jnp.asarray(
            rng.randint(0, 256, (b, s)).astype(np.int32)).reshape(
                (tr.n_micro, mb, s))
        runner = tr._mpmd_runner
        closed = runner.lint_jaxpr(x_micro, y_micro)
    finally:
        _flags.set_flags(old)
    return closed, dict(mesh=runner.stage_meshes[-1], donated=set())


def _target_serving(large_threshold=TARGET_THRESHOLD):
    from .targets import analyze_serving_decode

    return analyze_serving_decode(large_threshold=large_threshold)


def _target_disagg():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..models import GPTConfig, GPTForCausalLM
    from ..serving.disagg import PrefillWorker

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    worker = PrefillWorker(m, prompt_buckets=(32,))
    padded = jnp.zeros((1, 32), jnp.int32)
    closed = jax.make_jaxpr(worker._prefill._jit)(
        worker._params, padded, np.int32(7))
    return closed, dict(mesh=None)


def flow_summary(closed, mesh=None, large_threshold=TARGET_THRESHOLD):
    """Machine-readable communication summary of one traced program —
    the dict counterpart of the finding-producing passes, consumed by
    the plan-search cost model (analysis/cost_model.py).

    Collective payload bytes are summed per family with the per-device
    ring wire factor applied — ``2 (n-1)/n`` for reduce (psum and kin),
    ``(n-1)/n`` for exchange (all_gather/all_to_all/scatter), ``1`` for
    permute — where ``n`` is the product of the collective's axis sizes
    resolved against the enclosing shard_map's mesh (falling back to
    `mesh`); unresolvable axes get factor 1. Resharding-churn bytes sum
    the payloads of every :class:`_SpecFlow` churn event (a layout
    change re-materializes the value once on the wire). Trace-only,
    like everything else here."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    from .jaxpr_utils import is_literal

    fam_bytes = {"reduce": 0.0, "exchange": 0.0, "permute": 0.0}
    fam_counts = {"reduce": 0, "exchange": 0, "permute": 0}
    for eqn, path, env, sm_mesh in _iter_with_axes(jaxpr):
        p = eqn.primitive.name
        if p not in COLLECTIVE_PRIMS:
            continue
        fam = ("reduce" if p in REDUCE_PRIMS
               else "exchange" if p in EXCHANGE_PRIMS else "permute")
        payload = sum(
            _size(v) * getattr(getattr(v.aval, "dtype", None),
                               "itemsize", 4)
            for v in eqn.invars if not is_literal(v))
        n = 1
        for a in _axes_of(eqn):
            sz = _axis_size(a, sm_mesh, mesh)
            if sz:
                n *= int(sz)
        if fam == "reduce":
            factor = 2.0 * (n - 1) / n if n > 1 else 0.0
        elif fam == "exchange":
            factor = (n - 1) / n if n > 1 else 0.0
        else:
            factor = 1.0
        fam_bytes[fam] += payload * factor
        fam_counts[fam] += 1
    flow = _SpecFlow(large_threshold)
    flow.run(jaxpr)
    churn_bytes = sum(
        _size(var) * getattr(getattr(var.aval, "dtype", None),
                             "itemsize", 4)
        for _, _, _, var in flow.churn)
    return {
        "collective_bytes": fam_bytes,
        "collective_counts": fam_counts,
        "collective_bytes_total": sum(fam_bytes.values()),
        "resharding_churn_bytes": churn_bytes,
        "resharding_events": len(flow.churn),
    }


def _target_builders():
    """target name -> () -> (ClosedJaxpr, run_passes kwargs), for every
    jaxpr-producing sharding target (serving builds its own report)."""
    return {
        "gpt_train": lambda: _target_train("gpt"),
        "bert_train": lambda: _target_train("bert"),
        "ernie_train": lambda: _target_train("ernie"),
        "dp8_quantized": _target_dp8_quantized,
        "pipeline": _target_pipeline,
        "disagg": _target_disagg,
        "mpmd_train": _target_mpmd,
    }


def sharding_summaries(targets=None, large_threshold=TARGET_THRESHOLD):
    """{target: flow_summary dict} over the bundled distributed
    programs — per-program resharding-churn bytes and collective byte
    totals as plain data (the findings stay with sharding_reports).
    `targets` picks a subset; ``serving`` has no single jaxpr and is
    excluded from the default set."""
    builders = _target_builders()
    picked = tuple(targets) if targets is not None \
        else tuple(builders)
    unknown = [t for t in picked if t not in builders]
    if unknown:
        raise ValueError(f"unknown sharding summary target(s) {unknown}; "
                         f"choose from {sorted(builders)}")
    out = {}
    for name in picked:
        closed, kw = builders[name]()
        out[name] = flow_summary(closed, mesh=kw.get("mesh"),
                                 large_threshold=large_threshold)
    return out


def sharding_reports(targets=None, large_threshold=TARGET_THRESHOLD):
    """{target: AnalysisReport} for the bundled distributed programs,
    traced under their real meshes and run through the full pass battery
    (trace only — nothing compiles or executes)."""
    from .registry import run_passes
    from .targets import _trace_with_warnings

    picked = tuple(targets) if targets is not None else SHARDING_TARGETS
    unknown = [t for t in picked if t not in SHARDING_TARGETS]
    if unknown:
        raise ValueError(f"unknown sharding target(s) {unknown}; "
                         f"choose from {SHARDING_TARGETS}")
    builders = _target_builders()
    reports = {}
    for name in picked:
        if name == "serving":
            reports[name] = _target_serving(large_threshold)
            continue
        (closed, kw), warn_findings = _trace_with_warnings(builders[name])
        rep = run_passes(closed, name=name,
                         large_threshold=large_threshold, **kw)
        rep.extend(warn_findings)
        reports[name] = rep.sort()
    return reports
