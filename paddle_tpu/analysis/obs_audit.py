"""Observability-drift audit: code vs docs vs the metrics_dump contract.

The telemetry layer's value depends on its inventory staying true:
every metric family and span name the code can emit is documented in
docs/OBSERVABILITY.md, and everything the docs (or the
``tools/metrics_dump.py`` required-families lists) promise still exists
in code. Before ISSUE 12 that was reviewer vigilance; this pass makes
it mechanical:

  metric-undocumented : a ``monitor.counter/gauge/histogram`` family
      registered in code but missing from the OBSERVABILITY.md metric
      reference table.
  metric-doc-stale    : a reference-table row naming a family no code
      registers (the doc promises telemetry that is gone).
  span-undocumented   : a ``trace.span/start_span/emit`` name literal
      missing from the span reference table.
  span-doc-stale      : a span-table row with no emitting call site
      (dynamically-named families like ``collective/<op>`` are declared
      in :data:`DYNAMIC_SPANS` and accepted).
  required-family-gone: a family in metrics_dump's ``_REQUIRED`` /
      ``_REQUIRED_SERIES`` lists that no code registers — the CI smoke
      target would fail forever.

The docs side is parsed from the two audited tables in
docs/OBSERVABILITY.md (headings :data:`METRIC_TABLE_HEADING` and
:data:`SPAN_TABLE_HEADING`): first column, backticked name. Adding a
metric family = register it in code AND add its row; the contract gate
fails on either half alone.
"""
import ast
import os
import re

from .allowlist import allowed
from .registry import Finding

__all__ = ["RULES", "DYNAMIC_SPANS", "METRIC_TABLE_HEADING",
           "SPAN_TABLE_HEADING", "code_metric_families",
           "code_span_names", "doc_reference", "required_families",
           "audit_inventory", "audit_package"]

RULES = {
    "metric-undocumented": "error",
    "metric-doc-stale": "error",
    "span-undocumented": "error",
    "span-doc-stale": "error",
    "required-family-gone": "error",
}

METRIC_TABLE_HEADING = "## Metric family reference"
SPAN_TABLE_HEADING = "## Span name reference"

#: span families whose names are built at runtime (f-strings /
#: concatenation) — documented under a placeholder row the code harvest
#: cannot see. Keys are the exact doc-table spellings accepted.
DYNAMIC_SPANS = ("collective/<op>",)

#: modules whose counter/gauge/histogram *definitions* are the registry
#: machinery itself, not instrumentation call sites
_METRIC_DEF_EXEMPT = ("monitor/registry.py", "monitor/exporters.py")
#: the tracer's own module (docstring examples, the span constructors)
_SPAN_DEF_EXEMPT = ("trace/__init__.py",)

_METRIC_METHODS = ("counter", "gauge", "histogram")
_SPAN_METHODS = ("span", "start_span", "emit")
#: accepted receiver spellings — `_monitor.counter(...)` registers a
#: metric, `scan.counter(...)` or a bare `emit(...)` helper does not
_METRIC_RECEIVERS = ("monitor", "_monitor")
_SPAN_RECEIVERS = ("trace", "_trace")

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_/<>]*$")


def _receiver_last(node):
    """Last segment of an attribute call's receiver ('' for bare
    names): `_monitor.counter(..)` -> '_monitor',
    `paddle.trace.span(..)` -> 'trace'."""
    if not isinstance(node.func, ast.Attribute):
        return ""
    recv = node.func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    return recv.id if isinstance(recv, ast.Name) else ""


def _bare_telemetry_names(tree, methods, pkg_markers):
    """Method names the module imported FROM a telemetry module
    (`from ..monitor import counter`) — bare calls of those names are
    registrations too."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] in pkg_markers:
            out |= {a.asname or a.name for a in node.names
                    if a.name in methods}
    return out


def _harvest(sources, methods, receivers, exempt):
    """{name: [(rel, lineno)]} of literal first-arg call sites whose
    receiver is a telemetry module alias (`_monitor.counter(...)`), or
    a bare name imported from one (`from ..monitor import counter`);
    the monitor package's own front-end calls its helpers bare."""
    out = {}
    for rel, src in sources.items():
        norm = rel.replace(os.sep, "/")
        if any(norm.endswith(e) for e in exempt):
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        bare_ok = _bare_telemetry_names(tree, methods, receivers)
        if norm.endswith("monitor/__init__.py"):
            bare_ok |= set(methods)   # the registry front-end itself
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if isinstance(node.func, ast.Attribute):
                if node.func.attr not in methods \
                        or _receiver_last(node) not in receivers:
                    continue
            elif not (isinstance(node.func, ast.Name)
                      and node.func.id in bare_ok):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str) \
                    and _NAME_RE.match(first.value):
                out.setdefault(first.value, []).append((rel, node.lineno))
    return out


def code_metric_families(sources):
    return _harvest(sources, _METRIC_METHODS, _METRIC_RECEIVERS,
                    _METRIC_DEF_EXEMPT)


def code_span_names(sources):
    return _harvest(sources, _SPAN_METHODS, _SPAN_RECEIVERS,
                    _SPAN_DEF_EXEMPT)


_ROW_CELL_RE = re.compile(r"^\s*\|\s*`([^`]+)`")


def _table_rows(text, heading):
    """Backticked first-column names of the markdown table under
    `heading` (up to the next heading)."""
    rows = []
    in_section = False
    for line in text.splitlines():
        if line.startswith("#"):
            in_section = line.strip() == heading
            continue
        if not in_section:
            continue
        m = _ROW_CELL_RE.match(line)
        if m:
            name = m.group(1).split("{")[0].strip()
            if name and not name.startswith("-"):
                rows.append(name)
    return rows


def doc_reference(text):
    """(documented metric families, documented span names)."""
    return (_table_rows(text, METRIC_TABLE_HEADING),
            _table_rows(text, SPAN_TABLE_HEADING))


def required_families(dump_source):
    """Family names promised by metrics_dump's _REQUIRED /
    _REQUIRED_SERIES tables; {family: lineno}."""
    out = {}
    try:
        tree = ast.parse(dump_source)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if not names & {"_REQUIRED", "_REQUIRED_SERIES"}:
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for val in node.value.values:
            if not isinstance(val, (ast.Tuple, ast.List)):
                continue
            for el in val.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    out.setdefault(el.value, node.lineno)
                elif isinstance(el, (ast.Tuple, ast.List)) and el.elts:
                    fam = el.elts[0]
                    if isinstance(fam, ast.Constant) \
                            and isinstance(fam.value, str):
                        out.setdefault(fam.value, node.lineno)
    return out


def audit_inventory(sources, doc_text, dump_source="", doc_where=None,
                    dynamic_spans=DYNAMIC_SPANS):
    """Run the drift rules over harvested code + docs; [Finding]."""
    doc_where = doc_where or "docs/OBSERVABILITY.md"
    findings = []
    metrics = code_metric_families(sources)
    spans = code_span_names(sources)
    doc_metrics, doc_spans = doc_reference(doc_text)
    lines_by_rel = {rel: src.splitlines() for rel, src in sources.items()}

    def emit_code(rule, name, sites, msg):
        rel, lineno = sites[0]
        if not allowed(lines_by_rel.get(rel, ()), lineno, rule):
            findings.append(Finding(rule, RULES[rule], msg,
                                    where=f"{rel}:{lineno}"))

    for name, sites in sorted(metrics.items()):
        if name not in doc_metrics:
            emit_code("metric-undocumented", name, sites,
                      f"metric family {name!r} is registered in code but "
                      f"has no row in the {doc_where} metric reference "
                      f"table ({METRIC_TABLE_HEADING!r}) — document it "
                      "or mark a deliberately-private family with "
                      "`# lint: allow(undocumented-metric)`")
    for name in doc_metrics:
        if name not in metrics:
            findings.append(Finding(
                "metric-doc-stale", RULES["metric-doc-stale"],
                f"{doc_where} documents metric family {name!r} but no "
                "code registers it — the telemetry it promises is gone; "
                "drop the row (or restore the family)",
                where=f"{doc_where}:{name}"))
    for name, sites in sorted(spans.items()):
        if name not in doc_spans:
            emit_code("span-undocumented", name, sites,
                      f"span {name!r} is emitted in code but has no row "
                      f"in the {doc_where} span reference table "
                      f"({SPAN_TABLE_HEADING!r}) — document it or mark "
                      "it `# lint: allow(undocumented-span)`")
    for name in doc_spans:
        if name not in spans and name not in dynamic_spans:
            findings.append(Finding(
                "span-doc-stale", RULES["span-doc-stale"],
                f"{doc_where} documents span {name!r} but no call site "
                "emits it (dynamic families belong in "
                "analysis/obs_audit.py DYNAMIC_SPANS)",
                where=f"{doc_where}:{name}"))
    for name, lineno in sorted(required_families(dump_source).items()):
        if name not in metrics:
            findings.append(Finding(
                "required-family-gone", RULES["required-family-gone"],
                f"tools/metrics_dump.py requires family {name!r} but no "
                "code registers it — the smoke target can never pass",
                where=f"tools/metrics_dump.py:{lineno}"))
    findings.sort(key=lambda f: f.where)
    return findings


def audit_package(root=None):
    """The repo audit: paddle_tpu/ call sites vs docs/OBSERVABILITY.md
    vs tools/metrics_dump.py."""
    from .flag_audit import package_sources

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(root)
    sources = package_sources(root, include_tools=False)
    doc_path = os.path.join(repo, "docs", "OBSERVABILITY.md")
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    dump_path = os.path.join(repo, "tools", "metrics_dump.py")
    dump_source = ""
    if os.path.exists(dump_path):
        with open(dump_path, encoding="utf-8") as f:
            dump_source = f.read()
    return audit_inventory(sources, doc_text, dump_source)
