"""Measured-constant calibration of the plan cost model (ISSUE 17).

The static cost model (analysis/cost_model.py) prices compute and
communication against *nominal* constants — datasheet peak flops, a
device-kind HBM table, a fixed interconnect bandwidth. This module
closes ROADMAP item 4's named follow-on ("feed a banked BENCH
measurement back into the cost constants"): it least-squares fits the
*effective* constants out of perf-ledger rows
(monitor/perfledger.py) and emits a calibration table
``CostModel(constants=)`` consumes, so ``tools/plan_search.py
--calibrated`` ranks plans against the hardware the ledger actually
observed.

Fits (all through-origin least squares — the physically honest model,
``t ≈ work / rate``, has no intercept):

- **effective peak flops** from rows carrying ``flops_per_step`` +
  ``exec_ms`` (or ``step_ms``): minimizing ``Σ (t - f/P)²`` over the
  rate gives ``P = Σf² / Σ(f·t)``;
- **effective HBM bandwidth** from rows carrying ``bytes_per_step``
  (the executable's XLA ``bytes accessed``) + the same wall time — an
  upper-bound-coupled estimate (compute and memory share the step), so
  it is reported as *effective*, never datasheet;
- **per-collective-op wire bandwidth** from rows whose ``collectives``
  table carries TIMED entries (``{op: {"bytes": B, "ms": T}}`` — bench
  legs and synthetic rows; cumulative untimed tallies are skipped), one
  rate per op, plus a bytes-weighted aggregate ``net_bandwidth``.

Rows are grouped by the ledger's CORE env fingerprint — a laptop's rows
must never calibrate a TPU pod's cost model. Everything reports through
the graph_lint finding schema (``RULES`` below) so
``tools/perf_report.py --calibrate`` folds into the battery.

Manifest-lazy (analysis/import_graph.py LAZY_MODULES): nothing on a
plain trainer/engine path imports this module.
"""
import json
import math

from .registry import Finding
from ..monitor import perfledger as _pl

__all__ = ["RULES", "SCHEMA_VERSION", "MIN_ROWS", "fit_rate",
           "calibrate", "save_table", "load_table",
           "constants_for_cost_model"]

RULES = {
    # fewer matching rows than MIN_ROWS for a fit: the constant is
    # omitted, the nominal table stays in force
    "calib-insufficient-rows": "warning",
    # rows exist but none carry the fields a fit needs
    "calib-no-signal": "warning",
    # a fit produced a non-finite / non-positive rate (degenerate rows)
    "calib-fit-unstable": "warning",
}

#: calibration table schema version
SCHEMA_VERSION = 1

#: minimum (work, time) pairs before a fit is trusted
MIN_ROWS = 3


def _num(v):
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(float(v)))


def fit_rate(pairs):
    """Through-origin least squares of ``t ≈ work / rate`` over
    ``(work, t_seconds)`` pairs: ``rate = Σw² / Σ(w·t)``. Returns None
    on degenerate input (no positive-work/positive-time pairs)."""
    sww = swt = 0.0
    for w, t in pairs:
        if w > 0 and t > 0:
            sww += w * w
            swt += w * t
    if swt <= 0.0:
        return None
    return sww / swt


def calibrate(rows, env=None):
    """Fit the constants table from ledger rows filtered to one CORE env
    fingerprint (default: this process's). Returns ``(table,
    findings)`` — the table always exists; missing fits surface as
    warning findings and absent keys (CostModel falls back to nominal
    for those)."""
    fp = env if env is not None else _pl.env_fingerprint()
    key = _pl.fingerprint_key(fp)
    use = [r for r in rows
           if _pl.fingerprint_key(r.get("env") or {}) == key]
    findings = []
    flops_pairs, bytes_pairs = [], []
    wire_pairs = {}   # op -> [(bytes, s)]
    for r in use:
        m = r.get("metrics") or {}
        # exec_ms excludes compile resolution; only fall back to the
        # whole-step wall time for rows that did NOT resolve a compile
        t = m.get("exec_ms") if _num(m.get("exec_ms")) \
            else (None if m.get("cold") else m.get("step_ms"))
        t_s = float(t) / 1e3 if _num(t) and float(t) > 0 else None
        if t_s is not None:
            f = m.get("flops_per_step")
            if _num(f) and float(f) > 0:
                flops_pairs.append((float(f), t_s))
            b = m.get("bytes_per_step")
            if _num(b) and float(b) > 0:
                bytes_pairs.append((float(b), t_s))
        coll = m.get("collectives")
        if isinstance(coll, dict):
            for op, d in coll.items():
                if not isinstance(d, dict):
                    continue
                wb, wt = d.get("bytes"), d.get("ms")
                if _num(wb) and _num(wt) and float(wb) > 0 \
                        and float(wt) > 0:
                    wire_pairs.setdefault(str(op), []).append(
                        (float(wb), float(wt) / 1e3))

    constants = {}

    def _fit(name, pairs, signal):
        if len(pairs) < MIN_ROWS:
            rule = "calib-no-signal" if not pairs \
                else "calib-insufficient-rows"
            findings.append(Finding(
                rule, "warning",
                f"{name}: {len(pairs)} usable row(s) carrying {signal} "
                f"(need {MIN_ROWS}) — nominal constant stays in force",
                where=f"env:{key}"))
            return None
        rate = fit_rate(pairs)
        if rate is None or not math.isfinite(rate) or rate <= 0:
            findings.append(Finding(
                "calib-fit-unstable", "warning",
                f"{name}: degenerate fit over {len(pairs)} row(s) — "
                "nominal constant stays in force", where=f"env:{key}"))
            return None
        return rate

    peak = _fit("peak_flops", flops_pairs, "flops_per_step + wall time")
    if peak is not None:
        constants["peak_flops"] = peak
    hbm = _fit("hbm_bandwidth", bytes_pairs, "bytes_per_step + wall time")
    if hbm is not None:
        constants["hbm_bandwidth"] = hbm
    per_op = {}
    if not wire_pairs:
        findings.append(Finding(
            "calib-no-signal", "warning",
            "net_bandwidth: no row carries timed collective entries "
            "({op: {bytes, ms}}) — nominal interconnect bandwidth "
            "stays in force", where=f"env:{key}"))
    for op in sorted(wire_pairs):
        rate = _fit(f"net_bandwidth[{op}]", wire_pairs[op],
                    "timed collective bytes")
        if rate is not None:
            per_op[op] = rate
    if per_op:
        constants["net_bandwidth_per_op"] = per_op
        weights = {op: sum(w for w, _ in wire_pairs[op]) for op in per_op}
        total_w = sum(weights.values())
        constants["net_bandwidth"] = sum(
            per_op[op] * weights[op] for op in per_op) / total_w
    table = {
        "v": SCHEMA_VERSION,
        "rows": len(use),
        "rows_total": len(rows),
        "env": {k: fp.get(k) for k in _pl.CORE_FINGERPRINT},
        "fits": {
            "peak_flops": len(flops_pairs),
            "hbm_bandwidth": len(bytes_pairs),
            "net_bandwidth": {op: len(p) for op, p in
                              sorted(wire_pairs.items())},
        },
        "constants": constants,
    }
    return table, findings


def save_table(table, path):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(table, f, indent=1, sort_keys=True)
        f.write("\n")


def load_table(path):
    """Load a calibration table; raises ValueError on a foreign schema
    (a silently mis-read table would mis-price every plan)."""
    with open(path, "r", encoding="utf-8") as f:
        table = json.load(f)
    if not isinstance(table, dict) or table.get("v") != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: not a calibration table (want v={SCHEMA_VERSION}, "
            f"got {table.get('v') if isinstance(table, dict) else table!r})")
    return table


def constants_for_cost_model(table):
    """The subset of a table ``CostModel(constants=)`` recognizes."""
    c = table.get("constants") or {}
    return {k: c[k] for k in ("peak_flops", "hbm_bandwidth",
                              "net_bandwidth") if c.get(k)}
