"""Graph-analysis pass registry: static inspection of traced jaxprs.

Reference parity: the REGISTER_PASS layer (paddle/fluid/framework/ir — 107
graph passes that inspect and rewrite the IR before execution). XLA owns
rewriting here, so this package keeps the half the reference could not
delegate: *analysis* — static detection of correctness and performance
hazards in the traced program before it runs on the TPU (host syncs inside
hot loops, PRNG key reuse, silent dtype widening, dead graph regions,
recompilation triggers, collective drift, missed donations).

Two surfaces:
  - jaxpr passes (`registry.register_pass` + `run_passes`) over any traced
    function, Program, or Predictor;
  - an AST source linter (`source_lint`) with framework-specific rules run
    over paddle_tpu/ itself.

CLI: ``python tools/graph_lint.py --model gpt --json``; the tier-1 gate
(tests/test_graph_lint_gate.py) pins zero error-severity findings on the
bundled models and the serving decode step. See docs/ANALYSIS.md.
"""
from .registry import (  # noqa: F401
    AnalysisContext,
    AnalysisReport,
    Finding,
    SEVERITIES,
    register_pass,
    registered_passes,
    run_passes,
)
from .collectives import count_hlo_collectives  # noqa: F401
from . import passes  # noqa: F401  — registers the builtin pass battery
from . import sharding_flow  # noqa: F401  — registers the ISSUE 13 passes
from .source_lint import lint_path, lint_source  # noqa: F401
from .targets import analyze_model, analyze_serving_decode  # noqa: F401
from .sharding_flow import sharding_reports  # noqa: F401


def contract_reports(targets=None, handoff_baseline=None):
    """The contract-auditor battery (ISSUE 12 + 13): run the static
    contract passes over the repo; returns {target: AnalysisReport} for
    targets ``flags`` (flag_audit), ``imports`` (import_graph lazy
    closure), ``observability`` (obs_audit docs/code/metrics_dump
    drift), ``threads`` (the unlocked-thread-shared-write lint over
    THREAD_SHARED_MODULES), ``handoff`` (handoff_schema transfer-edge
    declarations vs tests/handoff_baseline.json), ``pallas``
    (pallas_audit kernel block/VMEM/accumulator budgets). `targets`
    picks a subset (None = all six — only the picked passes run).
    CLI: ``python tools/contract_audit.py``."""
    import os

    from . import flag_audit, import_graph, obs_audit
    from .source_lint import THREAD_SHARED_MODULES, lint_thread_discipline

    picked = ("flags", "imports", "observability", "threads", "handoff",
              "pallas") if targets is None else tuple(targets)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    reports = {}
    if "flags" in picked:
        rep = AnalysisReport(name="flags")
        rep.extend(flag_audit.audit_package())
        reports["flags"] = rep.sort()
    if "imports" in picked:
        rep = AnalysisReport(name="imports")
        rep.extend(import_graph.audit_package())
        reports["imports"] = rep.sort()
    if "observability" in picked:
        rep = AnalysisReport(name="observability")
        rep.extend(obs_audit.audit_package())
        reports["observability"] = rep.sort()
    if "threads" in picked:
        rep = AnalysisReport(name="threads")
        for rel, lock in sorted(THREAD_SHARED_MODULES.items()):
            path = os.path.join(pkg_root, rel)
            with open(path, encoding="utf-8") as f:
                rep.extend(lint_thread_discipline(f.read(), rel, lock))
        reports["threads"] = rep.sort()
    if "handoff" in picked:
        from . import handoff_schema

        rep = AnalysisReport(name="handoff")
        rep.extend(handoff_schema.audit_package(
            baseline_path=handoff_baseline))
        reports["handoff"] = rep.sort()
    if "pallas" in picked:
        from . import pallas_audit

        rep = AnalysisReport(name="pallas")
        rep.extend(pallas_audit.audit_package())
        reports["pallas"] = rep.sort()
    return reports


def contract_rules():
    """{rule: severity} over the source linter AND the contract-auditor
    passes — the one vocabulary --list-rules prints (with allow-marker
    spellings from analysis/allowlist.py). The ISSUE 13 jaxpr-level
    sharding rules ride along: one vocabulary across every surface."""
    from . import (cost_model, flag_audit, handoff_schema, import_graph,
                   obs_audit, pallas_audit, plan_search, sharding_flow,
                   source_lint)

    merged = {}
    for mod in (source_lint, flag_audit, import_graph, obs_audit,
                sharding_flow, handoff_schema, pallas_audit,
                cost_model, plan_search):
        merged.update(mod.RULES)
    return merged


def rule_table():
    """The --list-rules text both CLIs print (tools/contract_audit.py
    and tools/graph_lint.py): every rule, its severity, and every
    accepted allow-marker spelling — one implementation so the two
    surfaces can never drift."""
    from .allowlist import spellings

    lines = [f"{'rule':<34} {'severity':<9} allow-marker spelling(s)"]
    for rule, sev in sorted(contract_rules().items()):
        marks = ", ".join(f"# lint: allow({s})" for s in spellings(rule))
        lines.append(f"{rule:<34} {sev:<9} {marks}")
    return "\n".join(lines)
