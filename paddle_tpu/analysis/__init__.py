"""Graph-analysis pass registry: static inspection of traced jaxprs.

Reference parity: the REGISTER_PASS layer (paddle/fluid/framework/ir — 107
graph passes that inspect and rewrite the IR before execution). XLA owns
rewriting here, so this package keeps the half the reference could not
delegate: *analysis* — static detection of correctness and performance
hazards in the traced program before it runs on the TPU (host syncs inside
hot loops, PRNG key reuse, silent dtype widening, dead graph regions,
recompilation triggers, collective drift, missed donations).

Two surfaces:
  - jaxpr passes (`registry.register_pass` + `run_passes`) over any traced
    function, Program, or Predictor;
  - an AST source linter (`source_lint`) with framework-specific rules run
    over paddle_tpu/ itself.

CLI: ``python tools/graph_lint.py --model gpt --json``; the tier-1 gate
(tests/test_graph_lint_gate.py) pins zero error-severity findings on the
bundled models and the serving decode step. See docs/ANALYSIS.md.
"""
from .registry import (  # noqa: F401
    AnalysisContext,
    AnalysisReport,
    Finding,
    SEVERITIES,
    register_pass,
    registered_passes,
    run_passes,
)
from .collectives import count_hlo_collectives  # noqa: F401
from . import passes  # noqa: F401  — registers the builtin pass battery
from .source_lint import lint_path, lint_source  # noqa: F401
from .targets import analyze_model, analyze_serving_decode  # noqa: F401
