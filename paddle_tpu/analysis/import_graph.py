"""Static import-graph analysis: the lazy-import closure contract.

The framework's inert-by-default discipline says a plain (flags-unset)
trainer/engine process must never import the optional subsystems — the
compress module, the async dispatcher, the TPP kernel registry, the
numerics telescope, the parity harness, the flight recorder, the
federated tier, and the router/disagg serving layers. Ten
``test_*_gate.py`` files each pin ONE of those by spawning a subprocess
and asserting ``'x' not in sys.modules``; this module proves the whole
family at once, statically: build the module-level import graph of
``paddle_tpu/`` (function-local imports and PEP 562 ``__getattr__``
loaders are *lazy* edges), compute the eager closure of the plain
trainer/engine roots, and fail if any manifest-lazy module is inside it
— with the offending import chain in the finding. The subprocess pins
stay as belt-and-braces; this check catches the leak at lint time, with
provenance, for every module in the manifest including ones a future PR
adds.

Declaring a new lazy module = appending its dotted name to
:data:`LAZY_MODULES` (a ``pkg.sub`` entry covers the whole subtree).
A deliberate module-level import of a lazy module (e.g. the env-flag
arming hook in ``monitor/__init__``) carries
``# lint: allow(lazy-import)`` and is treated as a lazy (conditional)
edge.
"""
import ast
import os

from .allowlist import allowed
from .registry import Finding

__all__ = ["RULES", "LAZY_MODULES", "PLAIN_CLOSURE_ROOTS", "ImportGraph",
           "build_graph", "audit_package"]

RULES = {
    "lazy-module-leak": "error",
    "lazy-manifest-stale": "error",
}

#: the lazy-module manifest: none of these may be module-level-importable
#: from the plain trainer/engine closure. A name covers its subtree.
LAZY_MODULES = (
    "paddle_tpu.distributed.compress",       # int8 grad reduce (ISSUE 10)
    "paddle_tpu.distributed.async_dispatch", # StepHandle window (ISSUE 11)
    "paddle_tpu.ops.tpp",                    # Pallas micro-kernels (ISSUE 11)
    "paddle_tpu.monitor.numerics",           # numerics telescope (ISSUE 9)
    "paddle_tpu.monitor.blackbox",           # flight recorder (ISSUE 7/12)
    "paddle_tpu.testing.parity",             # A/B parity harness (ISSUE 9)
    "paddle_tpu.federated",                  # federated tier (ISSUE 8)
    "paddle_tpu.serving.router",             # multi-engine tier (ISSUE 6)
    "paddle_tpu.serving.disagg",             # prefill/decode split (ISSUE 6)
    "paddle_tpu.distributed.stage",          # MPMD stage runtime (ISSUE 15)
    "paddle_tpu.analysis.cost_model",        # plan-search pricing (ISSUE 16)
    "paddle_tpu.analysis.plan_search",       # plan enumerator (ISSUE 16)
    "paddle_tpu.monitor.perfledger",         # perf ledger + sentinel (ISSUE 17)
    "paddle_tpu.analysis.calibrate",         # measured-constant fits (ISSUE 17)
    "paddle_tpu.serving.paging",             # paged KV block pool (ISSUE 18)
    "paddle_tpu.distributed.elastic",        # auto-resume supervisor (ISSUE 19)
    "paddle_tpu.monitor.goodput",            # goodput wall-clock accountant (ISSUE 20)
)

#: what a plain trainer/engine process imports (the roots of the closure
#: the ten subprocess gates each rebuild by hand)
PLAIN_CLOSURE_ROOTS = (
    "paddle_tpu",
    "paddle_tpu.distributed.spmd",
    "paddle_tpu.distributed.mesh",
    "paddle_tpu.inference.serving",
)


class _ImportScan(ast.NodeVisitor):
    def __init__(self, lines):
        self.lines = lines
        self.stmts = []    # (node, lazy: bool)
        self._depth = 0

    def _visit_func(self, node):
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func
    visit_Lambda = _visit_func

    def _add(self, node):
        lazy = self._depth > 0 or allowed(self.lines, node.lineno,
                                          "lazy-module-leak")
        self.stmts.append((node, lazy))

    def visit_Import(self, node):
        self._add(node)

    def visit_ImportFrom(self, node):
        self._add(node)


class ImportGraph:
    """Module-level import graph of one python package tree.

    modules      : set of dotted module names found on disk
    eager[m]     : {dep: lineno} — module-level import edges
    lazy[m]      : {dep: lineno} — function-local / allow-marked edges
    """

    def __init__(self, package):
        self.package = package
        self.modules = set()
        self.packages = set()
        self.eager = {}
        self.lazy = {}

    # -- resolution ----------------------------------------------------------
    def _known(self, name):
        return name in self.modules

    def _parents(self, name):
        """Importing a.b.c executes a and a.b too."""
        out = []
        parts = name.split(".")
        for i in range(1, len(parts)):
            p = ".".join(parts[:i])
            if self._known(p):
                out.append(p)
        return out

    def _add_edge(self, table, src, dst, lineno):
        if dst == src or not self._known(dst):
            return
        table.setdefault(dst, lineno)
        for p in self._parents(dst):
            if p != src:
                table.setdefault(p, lineno)

    def _resolve(self, mod, node):
        """Yield dotted targets of one import statement in module `mod`."""
        if isinstance(node, ast.Import):
            for a in node.names:
                yield a.name
            return
        # ImportFrom
        if node.level == 0:
            base = node.module or ""
        else:
            # the package context of `mod`
            ctx = mod if mod in self.packages else mod.rsplit(".", 1)[0]
            parts = ctx.split(".")
            if node.level > 1:
                parts = parts[:len(parts) - (node.level - 1)]
            base = ".".join(parts)
            if node.module:
                base = f"{base}.{node.module}" if base else node.module
        if base:
            yield base
        for a in node.names:
            if a.name == "*":
                continue
            cand = f"{base}.{a.name}" if base else a.name
            if self._known(cand):
                yield cand

    def add_module(self, name, source, is_package=False):
        self.modules.add(name)
        if is_package:
            self.packages.add(name)
        self.eager.setdefault(name, {})
        self.lazy.setdefault(name, {})
        scan = _ImportScan(source.splitlines())
        try:
            scan.visit(ast.parse(source))
        except SyntaxError:
            return
        for node, lazy in scan.stmts:
            for dst in self._resolve(name, node):
                self._add_edge(self.lazy[name] if lazy else self.eager[name],
                               name, dst, node.lineno)

    # -- closure -------------------------------------------------------------
    def eager_closure(self, roots):
        """{module: shortest eager import chain (list of modules)} for
        everything reachable from `roots` over module-level edges."""
        out = {}
        frontier = [r for r in roots if self._known(r)]
        for r in frontier:
            out[r] = [r]
        while frontier:
            nxt = []
            for m in frontier:
                for dep in sorted(self.eager.get(m, ())):
                    if dep not in out:
                        out[dep] = out[m] + [dep]
                        nxt.append(dep)
            frontier = nxt
        return out

    def expand(self, manifest_entry):
        """Concrete modules covered by one manifest name (subtree)."""
        return sorted(m for m in self.modules
                      if m == manifest_entry
                      or m.startswith(manifest_entry + "."))


def build_graph(root=None, sources=None, package=None):
    """Build the ImportGraph of paddle_tpu/ (or of synthetic `sources`:
    {dotted module name: source}; package names ending in a component
    named '__init__' are not expected — pass packages via `package`-less
    dotted names and list them in sources with their submodules)."""
    if sources is not None:
        g = ImportGraph(package or "pkg")
        # first pass: register names so `_known` sees siblings
        pkgs = set()
        for name in sources:
            parts = name.split(".")
            for i in range(1, len(parts)):
                pkgs.add(".".join(parts[:i]))
        for name, src in sources.items():
            g.modules.add(name)
        g.packages |= {p for p in pkgs if p in g.modules}
        # a name that has submodules is a package
        for name in list(g.modules):
            if any(m.startswith(name + ".") for m in g.modules):
                g.packages.add(name)
        for name, src in sources.items():
            g.add_module(name, src, is_package=name in g.packages)
        return g
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg_name = os.path.basename(root)
    g = ImportGraph(pkg_name)
    entries = []   # (dotted, path, is_package)
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"
                       and os.path.exists(os.path.join(dirpath, d,
                                                       "__init__.py"))]
        rel = os.path.relpath(dirpath, root)
        base = pkg_name if rel == "." else \
            pkg_name + "." + rel.replace(os.sep, ".")
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            if fn == "__init__.py":
                entries.append((base, os.path.join(dirpath, fn), True))
            else:
                entries.append((f"{base}.{fn[:-3]}",
                                os.path.join(dirpath, fn), False))
    for name, _, is_pkg in entries:
        g.modules.add(name)
        if is_pkg:
            g.packages.add(name)
    for name, path, is_pkg in entries:
        with open(path, encoding="utf-8") as f:
            g.add_module(name, f.read(), is_package=is_pkg)
    return g


def audit_graph(g, manifest=LAZY_MODULES, roots=PLAIN_CLOSURE_ROOTS):
    """Check the lazy manifest against the eager closure; [Finding]."""
    findings = []
    closure = g.eager_closure(roots)
    for entry in manifest:
        concrete = g.expand(entry)
        if not concrete:
            findings.append(Finding(
                "lazy-manifest-stale", RULES["lazy-manifest-stale"],
                f"lazy-module manifest names {entry!r} but no such "
                "module exists — remove the entry or fix the name",
                where="analysis/import_graph.py:LAZY_MODULES"))
            continue
        for mod in concrete:
            chain = closure.get(mod)
            if chain is not None:
                findings.append(Finding(
                    "lazy-module-leak", RULES["lazy-module-leak"],
                    f"manifest-lazy module {mod} is eagerly importable "
                    "from the plain trainer/engine closure via "
                    f"{' -> '.join(chain)} — move the import into the "
                    "consuming function (or behind a PEP 562 "
                    "__getattr__); a deliberate flag-guarded module-"
                    "level import carries `# lint: allow(lazy-import)`",
                    where=mod))
    for r in roots:
        if not g._known(r):
            findings.append(Finding(
                "lazy-manifest-stale", RULES["lazy-manifest-stale"],
                f"plain-closure root {r!r} names no existing module",
                where="analysis/import_graph.py:PLAIN_CLOSURE_ROOTS"))
    findings.sort(key=lambda f: f.where)
    return findings


def audit_package(root=None):
    """The repo audit: graph paddle_tpu/ and check the manifest."""
    return audit_graph(build_graph(root))
