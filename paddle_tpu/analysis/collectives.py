"""Collective-stream accounting, shared between the analysis passes and
the perf-budget gate (tests/test_perf_budgets.py imports
count_hlo_collectives — the exact-HLO-count machinery lived there first).

EQuARX (arXiv:2506.17615) motivates this surface: on TPU slices the
collective stream IS the scaling budget, so an unplanned all-gather is a
regression worth failing a build over, and it is visible statically.
"""
import re

# post-partitioning HLO op spellings (start variants cover async pairs)
_HLO_KINDS = {
    "all-reduce": r"all-reduce\(|all-reduce-start\(",
    "all-gather": r"all-gather\(|all-gather-start\(",
    "reduce-scatter": r"reduce-scatter\(",
    "all-to-all": r"all-to-all\(",
    "collective-permute": r"collective-permute\(|collective-permute-start\(",
}

HLO_COLLECTIVE_KINDS = tuple(_HLO_KINDS)

# jaxpr-level collective primitives -> the HLO family they lower into
JAXPR_COLLECTIVES = {
    "psum": "all-reduce", "pmin": "all-reduce", "pmax": "all-reduce",
    "all_gather": "all-gather", "reduce_scatter": "reduce-scatter",
    "psum_scatter": "reduce-scatter", "all_to_all": "all-to-all",
    "ppermute": "collective-permute", "pgather": "all-gather",
}


def count_hlo_collectives(hlo_text, kinds=("all-reduce", "all-gather",
                                           "reduce-scatter")):
    """Exact collective-op counts in compiled HLO text.

    Default kinds match the historical perf-budget recording format, so
    existing tests/perf_budgets.json baselines stay byte-compatible.
    """
    return {k: len(re.findall(_HLO_KINDS[k], hlo_text)) for k in kinds}


def count_jaxpr_collectives(jaxpr):
    """Collective eqn counts (by HLO family) at every nesting depth."""
    from .jaxpr_utils import iter_eqns

    out = {}
    for eqn, _ in iter_eqns(jaxpr):
        fam = JAXPR_COLLECTIVES.get(eqn.primitive.name)
        if fam is not None:
            out[fam] = out.get(fam, 0) + 1
    return out


# -- the quantized reduce family (distributed/compress.py) ---------------------
# A wire-compressed all-reduce decomposes into a reduce-scatter phase (the
# int8 shard exchange — all_to_all of the quantized payload, or a
# quantized psum_scatter) and an all-gather phase (the re-quantized
# reduced shards going back out). The payload dtype is the tell: the
# exchange ops carry the int8 wire format, while their small float32
# scale side-channels ride as ordinary all_to_all/all_gather eqns.

QUANTIZED_WIRE_DTYPES = ("int8", "uint8")

#: jaxpr exchange primitives a quantized reduce is built from, mapped to
#: the phase they implement when the payload is a wire dtype
_QUANTIZED_PHASES = {
    "all_to_all": "quantized-reduce-scatter",
    "psum_scatter": "quantized-reduce-scatter",
    "all_gather": "quantized-all-gather",
}


def count_quantized_collectives(jaxpr):
    """Exact counts of the wire-compressed exchange pair: all_to_all/
    psum_scatter ("quantized-reduce-scatter") and all_gather
    ("quantized-all-gather") eqns whose payload dtype is int8/uint8, at
    every nesting depth. Zero for any program that never quantized a
    collective — tests/test_perf_budgets.py pins the dp8 quantized train
    step to exactly one of each."""
    from .jaxpr_utils import iter_eqns

    out = {fam: 0 for fam in ("quantized-reduce-scatter",
                              "quantized-all-gather")}
    for eqn, _ in iter_eqns(jaxpr):
        fam = _QUANTIZED_PHASES.get(eqn.primitive.name)
        if fam is None or not eqn.invars:
            continue
        aval = getattr(eqn.invars[0], "aval", None)
        if aval is not None and str(getattr(aval, "dtype", "")) in \
                QUANTIZED_WIRE_DTYPES:
            out[fam] += 1
    return out
