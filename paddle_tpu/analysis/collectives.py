"""Collective-stream accounting, shared between the analysis passes and
the perf-budget gate (tests/test_perf_budgets.py imports
count_hlo_collectives — the exact-HLO-count machinery lived there first).

EQuARX (arXiv:2506.17615) motivates this surface: on TPU slices the
collective stream IS the scaling budget, so an unplanned all-gather is a
regression worth failing a build over, and it is visible statically.
"""
import re

# post-partitioning HLO op spellings (start variants cover async pairs)
_HLO_KINDS = {
    "all-reduce": r"all-reduce\(|all-reduce-start\(",
    "all-gather": r"all-gather\(|all-gather-start\(",
    "reduce-scatter": r"reduce-scatter\(",
    "all-to-all": r"all-to-all\(",
    "collective-permute": r"collective-permute\(|collective-permute-start\(",
}

HLO_COLLECTIVE_KINDS = tuple(_HLO_KINDS)

# jaxpr-level collective primitives -> the HLO family they lower into
JAXPR_COLLECTIVES = {
    "psum": "all-reduce", "pmin": "all-reduce", "pmax": "all-reduce",
    "all_gather": "all-gather", "reduce_scatter": "reduce-scatter",
    "psum_scatter": "reduce-scatter", "all_to_all": "all-to-all",
    "ppermute": "collective-permute", "pgather": "all-gather",
}


def count_hlo_collectives(hlo_text, kinds=("all-reduce", "all-gather",
                                           "reduce-scatter")):
    """Exact collective-op counts in compiled HLO text.

    Default kinds match the historical perf-budget recording format, so
    existing tests/perf_budgets.json baselines stay byte-compatible.
    """
    return {k: len(re.findall(_HLO_KINDS[k], hlo_text)) for k in kinds}


def count_jaxpr_collectives(jaxpr):
    """Collective eqn counts (by HLO family) at every nesting depth."""
    from .jaxpr_utils import iter_eqns

    out = {}
    for eqn, _ in iter_eqns(jaxpr):
        fam = JAXPR_COLLECTIVES.get(eqn.primitive.name)
        if fam is not None:
            out[fam] = out.get(fam, 0) + 1
    return out
