"""Flag-contract audit: static verification of the FLAGS_* discipline.

Every feature in this framework hides behind a construction-time flag
(docs/OBSERVABILITY.md, docs/PERF.md) — the discipline the ten
``test_*_gate.py`` files each re-prove by hand for one flag. This pass
audits EVERY ``define_flag``/``get_flag`` site in the package at once:

  orphan-flag-unread      : a flag defined but read nowhere (package,
      tools/, bench.py) — dead configuration surface. A deliberate
      reference-parity stub carries ``# lint: allow(orphan-flag)``.
  orphan-flag-undefined   : a literal ``get_flag("x")`` of a name no
      module defines — the read silently returns its local default and
      drifts from whatever the definer later picks.
  flag-missing-help       : ``define_flag`` without a non-empty help
      string — ``paddle.get_flags`` and the docs tables both surface it.
  flag-default-conflict   : two modules define the same flag with
      DIFFERENT literal defaults (the runtime registry also raises on
      this since ISSUE 12 — the static form names both sites).
  structural-flag-key-miss: a STRUCTURAL flag (one that changes the
      compiled program or the state layout) whose consumption never
      reaches an ``_exec_key``/AOT ``extra_key`` expression — toggling
      it would silently reuse a stale executable.
  hot-path-flag-read      : a structural flag re-read inside a per-step
      hot-path function (source_lint.HOT_PATHS) outside the sanctioned
      ``*_active`` cached-one-boolean checkers — construction-consumed
      flags must be compared against the cached value, not re-derived
      per step.
  flag-default-drift      : ``get_flag("x", local_default)`` whose local
      default differs from the defining site's — the two sites disagree
      about what "unset" means (warning).
  lazy-flag-eager-read    : a flag defined ONLY inside a manifest-lazy
      module (import_graph.LAZY_MODULES) but read from outside it — the
      read can run before the definition exists (warning; the fix is
      the flags.py pattern FLAGS_numerics uses).

Structural flags are DECLARED in :data:`STRUCTURAL_FLAGS` — adding a
flag that changes the traced program means adding it here AND routing it
into an exec-key expression (docs/ANALYSIS.md "Contract auditor" shows
the recipe).
"""
import ast
import os

from .allowlist import allowed
from .registry import Finding

__all__ = ["RULES", "STRUCTURAL_FLAGS", "KEY_FUNCS", "collect",
           "audit_inventory", "audit_package", "package_sources"]

RULES = {
    "orphan-flag-unread": "error",
    "orphan-flag-undefined": "error",
    "flag-missing-help": "error",
    "flag-default-conflict": "error",
    "structural-flag-key-miss": "error",
    "hot-path-flag-read": "error",
    "flag-default-drift": "warning",
    "lazy-flag-eager-read": "warning",
}

#: flags whose value changes the compiled program's identity or the
#: trainer's state layout: each MUST reach an _exec_key / AOT extra_key
#: expression so a toggle recompiles instead of reusing a stale
#: executable. Declare new structural flags here (the contract gate
#: fails until the flag actually joins a key expression).
STRUCTURAL_FLAGS = (
    "check_nan_inf",
    "numerics",
    "quantized_allreduce",
    "quantized_allreduce_bits",
    "quantized_allreduce_min_size",
    "shard_weight_update",
    "overlap_grad_comm",
    "use_bfloat16",
    "flash_attention_block",
    "mpmd",
    "paged_kv",
    "elastic",
)

#: function names whose bodies ARE executable-identity expressions —
#: anything referenced inside them (or inside an ``extra_key=`` call
#: keyword) counts as reaching the key
KEY_FUNCS = ("_exec_key", "_cache_key", "_exec_key_and_example")

_MISSING = object()


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _literal(node, default=_MISSING):
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return default


def _target_idents(targets):
    """Identifier names assigned by an assignment statement: plain names
    and attribute leaf names (``self._qar_bits`` -> ``_qar_bits``)."""
    out = set()
    for t in targets:
        for el in ast.walk(t):
            if isinstance(el, ast.Name):
                out.add(el.id)
            elif isinstance(el, ast.Attribute):
                out.add(el.attr)
    return out


def _refs(node):
    """Every identifier / attribute / string constant under `node`."""
    out = set()
    for el in ast.walk(node):
        if isinstance(el, ast.Name):
            out.add(el.id)
        elif isinstance(el, ast.Attribute):
            out.add(el.attr)
        elif isinstance(el, ast.Constant) and isinstance(el.value, str):
            out.add(el.value)
    return out


class _Scan(ast.NodeVisitor):
    """One module's flag inventory (defines / reads / key references)."""

    def __init__(self, rel, lines):
        self.rel = rel
        self.lines = lines
        self.defines = []      # (name, lineno, default_literal, help_ok)
        self.reads = []        # (name, lineno, func, in_key, default_lit)
        self.key_refs = set()  # identifiers/strings inside key contexts
        self.flag_tables = {}  # NAME -> [flag names] (module-level)
        self.carrier_map = {}  # func name -> idents assigned from its call
        self._funcs = []
        self._key_depth = 0
        self._assign_targets = []

    # -- scoping ------------------------------------------------------------
    def _visit_func(self, node):
        keyed = node.name in KEY_FUNCS
        if keyed:
            self._key_depth += 1
            self.key_refs |= _refs(node)
        self._funcs.append(node.name)
        self.generic_visit(node)
        self._funcs.pop()
        if keyed:
            self._key_depth -= 1

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _visit_assign(self, node, targets, value):
        if value is not None:
            # module-level tuple-of-strings flag table (_KEYED_FLAGS)
            if not self._funcs and isinstance(value, (ast.Tuple, ast.List)) \
                    and targets and isinstance(targets[0], ast.Name):
                names = [_literal(el) for el in value.elts]
                if names and all(isinstance(n, str) for n in names):
                    self.flag_tables[targets[0].id] = names
            # carrier hop: x, self._y = self._resolve_compress()  — the
            # call's enclosing function already carries the flag; its
            # assignment targets carry it one hop further
            for el in ast.walk(value):
                if isinstance(el, ast.Call):
                    fn = _dotted(el.func).split(".")[-1]
                    if fn:
                        self.carrier_map.setdefault(fn, set()).update(
                            _target_idents(targets))
        self._assign_targets.append(targets)
        self.generic_visit(node)
        self._assign_targets.pop()

    def visit_Assign(self, node):
        self._visit_assign(node, node.targets, node.value)

    def visit_AnnAssign(self, node):
        self._visit_assign(node, [node.target], node.value)

    def visit_AugAssign(self, node):
        self._visit_assign(node, [node.target], node.value)

    # -- call sites ----------------------------------------------------------
    def visit_Call(self, node):
        last = _dotted(node.func).split(".")[-1]
        if last == "define_flag" and node.args:
            name = _literal(node.args[0])
            if isinstance(name, str):
                default = _literal(node.args[1]) if len(node.args) > 1 \
                    else _MISSING
                help_node = node.args[2] if len(node.args) > 2 else None
                for kw in node.keywords:
                    if kw.arg == "help_str":
                        help_node = kw.value
                help_lit = None if help_node is None \
                    else _literal(help_node, default=None)
                # a non-literal help expression counts as present
                help_ok = help_node is not None and (
                    help_lit is None and not isinstance(help_node,
                                                        ast.Constant)
                    or bool(help_lit))
                self.defines.append(
                    (name, node.lineno, default, help_ok))
        elif last == "get_flag" and node.args:
            name = _literal(node.args[0])
            if isinstance(name, str):
                default = _literal(node.args[1]) if len(node.args) > 1 \
                    else _MISSING
                func = self._funcs[-1] if self._funcs else None
                targets = set()
                for ts in self._assign_targets:
                    targets |= _target_idents(ts)
                self.reads.append({
                    "name": name, "lineno": node.lineno, "func": func,
                    "in_key": self._key_depth > 0, "default": default,
                    "targets": targets})
        elif last == "get_flags" and node.args:
            names = _literal(node.args[0])
            if isinstance(names, str):
                names = [names]
            if isinstance(names, (list, tuple)):
                for n in names:
                    if isinstance(n, str):
                        self.reads.append({
                            "name": n, "lineno": node.lineno,
                            "func": self._funcs[-1] if self._funcs
                            else None, "in_key": self._key_depth > 0,
                            "default": _MISSING, "targets": set()})
        for kw in node.keywords:
            if kw.arg == "extra_key":
                self.key_refs |= _refs(kw.value)
        self.generic_visit(node)


def package_sources(root=None, include_tools=True):
    """{repo-relative path: source} for paddle_tpu/ (defines + reads)
    plus tools/ and bench.py (reads only live there too)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(root)
    out = {}
    scan_dirs = [root]
    if include_tools:
        tools = os.path.join(repo, "tools")
        if os.path.isdir(tools):
            scan_dirs.append(tools)
    for d in scan_dirs:
        for dirpath, dirnames, files in os.walk(d):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            for fn in sorted(files):
                if fn.endswith(".py"):
                    path = os.path.join(dirpath, fn)
                    with open(path, encoding="utf-8") as f:
                        out[os.path.relpath(path, repo)] = f.read()
    if include_tools:
        bench = os.path.join(repo, "bench.py")
        if os.path.exists(bench):
            with open(bench, encoding="utf-8") as f:
                out["bench.py"] = f.read()
    return out


def collect(sources):
    """Parse every module; returns {rel: _Scan} (unparseable skipped —
    the source linter owns syntax errors)."""
    scans = {}
    for rel, src in sources.items():
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        s = _Scan(rel, src.splitlines())
        s.visit(tree)
        scans[rel] = s
    return scans


def _module_name(rel):
    """'paddle_tpu/distributed/spmd.py' -> 'paddle_tpu.distributed.spmd'"""
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = rel.replace(os.sep, "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def audit_inventory(scans, structural=STRUCTURAL_FLAGS, hot_paths=None,
                    lazy_modules=None):
    """Run every flag rule over collected scans; returns [Finding].

    hot_paths: {rel-to-package path: {func names}} (default:
    source_lint.HOT_PATHS); lazy_modules: manifest of lazily-imported
    module names (default: import_graph.LAZY_MODULES).
    """
    if hot_paths is None:
        from .source_lint import HOT_PATHS as hot_paths
    if lazy_modules is None:
        from .import_graph import LAZY_MODULES as lazy_modules
    findings = []

    def emit(rule, scan, lineno, msg):
        if not allowed(scan.lines, lineno, rule):
            findings.append(Finding(rule, RULES[rule], msg,
                                    where=f"{scan.rel}:{lineno}"))

    defines = {}   # name -> [(scan, lineno, default, help_ok)]
    reads = {}     # name -> [(scan, read-dict)]
    key_refs = set()
    carrier_map = {}
    for scan in scans.values():
        key_refs |= scan.key_refs
        for name, lineno, default, help_ok in scan.defines:
            defines.setdefault(name, []).append(
                (scan, lineno, default, help_ok))
        for r in scan.reads:
            reads.setdefault(r["name"], []).append((scan, r))
        for fn, targets in scan.carrier_map.items():
            carrier_map.setdefault(fn, set()).update(targets)
    # flag-name tables (module-level `X_FLAGS = ("a", "b")`) referenced
    # from a key context count as key-reaching reads of each name — the
    # aot.py _KEYED_FLAGS loop reads flags with a non-literal name
    for scan in scans.values():
        for tname, names in scan.flag_tables.items():
            if tname in key_refs:
                for n in names:
                    reads.setdefault(n, []).append(
                        (scan, {"name": n, "lineno": 0, "func": None,
                                "in_key": True, "default": _MISSING,
                                "targets": set()}))

    # hot-path membership is PER FILE: HOT_PATHS keys are paths relative
    # to the paddle_tpu package root while scans carry repo-relative
    # paths — match on the suffix so a tools/ script defining its own
    # `step()` never collides with the trainer's
    _hot_norm = {k.replace(os.sep, "/"): frozenset(v)
                 for k, v in (hot_paths or {}).items()}

    def hot_funcs_for(rel):
        norm = rel.replace(os.sep, "/")
        for key, funcs in _hot_norm.items():
            if norm == key or norm.endswith("/" + key):
                return funcs
        return frozenset()

    lazy_modules = tuple(lazy_modules or ())

    # -- per-define rules ----------------------------------------------------
    for name, sites in sorted(defines.items()):
        for scan, lineno, default, help_ok in sites:
            if not help_ok:
                emit("flag-missing-help", scan, lineno,
                     f"FLAGS_{name} is defined without a help string — "
                     "paddle.get_flags and the docs flag tables surface "
                     "it; say what the flag does")
        if name not in reads:
            scan, lineno, _, _ = sites[0]
            emit("orphan-flag-unread", scan, lineno,
                 f"FLAGS_{name} is defined but never read (package, "
                 "tools/, bench.py) — dead configuration surface; wire "
                 "it or delete it (a deliberate reference-parity stub "
                 "carries `# lint: allow(orphan-flag)` with a comment)")
        lits = [(s, ln, d) for s, ln, d, _ in sites if d is not _MISSING]
        if lits:
            s0, ln0, d0 = lits[0]
            for s, ln, d in lits[1:]:
                # repr-distinct: False/0/0.0 are three different
                # contracts (define_flag's env parsing keys off type)
                if repr(d) != repr(d0):
                    emit("flag-default-conflict", s, ln,
                         f"FLAGS_{name} re-defined with default {d!r} "
                         f"but {s0.rel}:{ln0} says {d0!r} — whichever "
                         "module imports first silently wins; one "
                         "definition must own the default")

    # -- per-read rules ------------------------------------------------------
    for name, sites in sorted(reads.items()):
        if name not in defines:
            scan, r = sites[0]
            if r["lineno"]:
                emit("orphan-flag-undefined", scan, r["lineno"],
                     f"get_flag({name!r}) but no module defines "
                     f"FLAGS_{name} — the read silently returns its "
                     "local default; define_flag it where it is owned")
            continue
        def_default = next((d for _, _, d, _ in defines[name]
                            if d is not _MISSING), _MISSING)
        def_modules = {_module_name(s.rel) for s, _, _, _ in defines[name]}
        lazy_defs = def_modules and all(
            any(m == lm or m.startswith(lm + ".") for lm in lazy_modules)
            for m in def_modules)
        for scan, r in sites:
            if not r["lineno"]:
                continue
            # repr-distinct like flag-default-conflict and the runtime
            # define_flag check: False/0/0.0 are three different
            # contracts (env parsing keys off the default's type)
            if def_default is not _MISSING and r["default"] is not _MISSING \
                    and repr(r["default"]) != repr(def_default):
                emit("flag-default-drift", scan, r["lineno"],
                     f"get_flag({name!r}, {r['default']!r}) disagrees "
                     f"with the defining default {def_default!r} — the "
                     "two sites see different values while the flag is "
                     "unset")
            # tools/ and bench.py are entrypoints that import their lazy
            # subsystem explicitly before touching its flags — the
            # ordering hazard is package-internal
            if lazy_defs and scan.rel.split(os.sep)[0].split("/")[0] \
                    == "paddle_tpu" \
                    and _module_name(scan.rel) not in def_modules:
                emit("lazy-flag-eager-read", scan, r["lineno"],
                     f"FLAGS_{name} is defined only inside lazy module"
                     f"(s) {sorted(def_modules)} but read from "
                     f"{scan.rel} — the read can run before the "
                     "definition exists; define the flag in flags.py "
                     "(the FLAGS_numerics pattern)")
            if name in structural and r["func"] in hot_funcs_for(scan.rel) \
                    and not (r["func"] or "").endswith("_active"):
                emit("hot-path-flag-read", scan, r["lineno"],
                     f"structural FLAGS_{name} re-read inside per-step "
                     f"hot path {r['func']}: construction-consumed "
                     "flags are compared against the cached boolean in "
                     "a *_active checker, never re-derived per step")

    # -- structural reach ----------------------------------------------------
    for name in structural:
        if name not in defines:
            continue   # orphan rules already cover it
        sites = reads.get(name, ())
        reached = False
        carriers = set()
        for scan, r in sites:
            if r["in_key"]:
                reached = True
                break
            if r["func"]:
                carriers.add(r["func"])
            carriers |= r["targets"]
        if not reached:
            hop = set(carriers)
            for fn in list(carriers):
                hop |= carrier_map.get(fn, set())
            reached = bool(hop & key_refs) or name in key_refs
        if not reached:
            scan, lineno, _, _ = defines[name][0]
            emit("structural-flag-key-miss", scan, lineno,
                 f"structural FLAGS_{name} never reaches an _exec_key / "
                 "AOT extra_key expression: toggling it would reuse a "
                 "stale executable — join it to the key (docs/ANALYSIS.md "
                 "\"Contract auditor\") or remove it from "
                 "STRUCTURAL_FLAGS if it truly cannot change the "
                 "compiled program")
    findings.sort(key=lambda f: f.where)
    return findings


def audit_package(root=None):
    """The repo audit: scan paddle_tpu/ (+tools/, bench.py) and run every
    rule. Returns [Finding]."""
    return audit_inventory(collect(package_sources(root)))
