"""Static cost model for auto-parallel plan search (ROADMAP item 4).

The reference Paddle picks a distributed strategy by trial runs over
fleet configs; here the strategy is priced WITHOUT executing anything,
by composing the static layers the repo already has:

- **compute** comes from the cost registry (trace/costs.py): the tiny
  bundled trainer step is jit-lowered once per model and XLA's
  ``cost_analysis()`` supplies total FLOPs / bytes accessed (trace +
  lower only — nothing runs). The per-device roofline is
  ``max(flops/peak_flops, bytes/hbm_bw)`` with the pipeline bubble
  factor ``(pp - 1 + n_micro) / n_micro`` on pipelined plans.
- **communication** comes from the sharding-flow analyzer when the
  plan's traced program carries explicit collectives (the shard_map
  paths: quantized all-reduce, pipeline ppermute) — see
  :func:`sharding_flow.flow_summary` — and from the documented analytic
  ring term ``2 (n-1)/n × grad bytes`` when the collective is
  XLA-inserted (plain-dp pjit carries no collective eqns to measure).
  Stage-edge bytes always come from the declared transfer schema via
  :func:`handoff_schema.wire_bytes` (dense vs the measured
  ``4 / (1 + 4/D)`` int8 ratio; grad edges stay dense — the schema
  says so, not this module).
- **memory** is priced per device (params + optimizer state + live
  activations + the quantized reduce's error-feedback residuals)
  against an HBM budget, and the per-stage activation working set is
  pushed through the SAME Pallas VMEM accounting registered kernels
  use (:func:`pallas_audit.audit_tile`, 16 MiB/core, streamed buffers
  double-buffered).

The model is deliberately coarse — it ranks candidate partitionings of
the CPU-shrunk bundled models, it does not predict wall seconds — but
every term is monotone in the thing it prices (more compress => fewer
wire bytes, bigger dp => smaller per-device HBM), which the planted
tests in tests/test_analysis_passes.py pin.

Manifest-lazy (analysis/import_graph.py LAZY_MODULES): a plain trainer
never imports this module; tools/plan_search.py and graph_lint --plan
reach it function-locally.
"""
import numpy as np

from .registry import Finding

__all__ = ["RULES", "Plan", "ModelProfile", "CostModel",
           "int8_wire_ratio", "GLOBAL_BATCH", "SEQ_LEN",
           "DEFAULT_HBM_BYTES"]

RULES = {
    "plan-invalid-config": "error",
    "plan-hbm-over-budget": "error",
}

#: fixed global batch every candidate plan divides (strong scaling —
#: this is what makes "bigger dp" buy anything at all); matches the
#: dp8 shape of the bundled sharding targets (b = 2 * 8, s = 16)
GLOBAL_BATCH = 16
SEQ_LEN = 16

#: per-device HBM budget the memory term is checked against. The
#: bundled tiny models sit ~6 orders of magnitude under it; the planted
#: tests and the CLI's --hbm-gb shrink it to exercise the rejection.
DEFAULT_HBM_BYTES = 16 << 30

#: per-message launch overhead charged per collective / edge transfer.
#: Deliberately small relative to the wire terms even at the bundled
#: tiny-model scale: byte totals decide the ranking, message counts
#: only break ties (a latency constant big enough to matter at CI
#: shapes would invert the compress-wins ordering that holds at real
#: shapes, where grads are GBs and launches stay microseconds)
LINK_LATENCY_S = 1e-7

#: live-activation multiple of one layer's boundary activation (attn
#: scores + mlp intermediates kept for backward, coarse)
ACT_LIVE_FACTOR = 4

#: the quantized all-reduce's per-block scale granularity
#: (distributed/compress.py; blocks of 256 share one float32 scale)
QAR_BLOCK = 256

#: interconnect bytes/s the comm seconds are priced at. Nominal — on
#: the CPU test harness only the RELATIVE ordering of plans matters,
#: and every plan is priced with the same constant.
NOMINAL_NET_BW = 50e9


def int8_wire_ratio(d):
    """Dense-float32 over int8-wire byte ratio for a row of ``d``
    elements under the row codec (int8 values + one float32 scale per
    row): ``4 / (1 + 4/d)`` — 3.94x at d=256, 3.76x at d=64. The same
    ratio distributed/stage.py documents for StageEdge compress=8."""
    d = int(d)
    if d <= 0:
        raise ValueError(f"row length must be positive, got {d}")
    return 4.0 / (1.0 + 4.0 / d)


class Plan:
    """One candidate partitioning of a bundled model.

    dp/mp/pp are mesh axis sizes (1 = axis absent); ``n_micro`` is the
    pipeline micro-batch count (pp plans only), ``stage_layers`` the
    per-stage layer index lists (equal cuts from the enumerator);
    ``quantized_allreduce`` arms the int8 dp grad reduce,
    ``edge_compress`` (None | 8) the forward stage-edge codec.
    ``compress_grad_edge`` exists so a deliberately-bad plan can ask
    for the thing the grad-edge schema forbids — the verifier rejects
    it through handoff_schema.validate, never silently.
    """

    __slots__ = ("dp", "mp", "pp", "n_micro", "stage_layers",
                 "quantized_allreduce", "edge_compress",
                 "compress_grad_edge")

    def __init__(self, dp=1, mp=1, pp=1, n_micro=None, stage_layers=None,
                 quantized_allreduce=False, edge_compress=None,
                 compress_grad_edge=False):
        self.dp = int(dp)
        self.mp = int(mp)
        self.pp = int(pp)
        self.n_micro = int(n_micro) if n_micro else (self.pp
                                                     if self.pp > 1 else 1)
        self.stage_layers = (None if stage_layers is None
                             else [list(s) for s in stage_layers])
        self.quantized_allreduce = bool(quantized_allreduce)
        self.edge_compress = edge_compress
        self.compress_grad_edge = bool(compress_grad_edge)

    @property
    def mesh_axes(self):
        """(axis_names, axis_sizes) of the mesh this plan runs on."""
        names, sizes = [], []
        for n, s in (("dp", self.dp), ("mp", self.mp), ("pp", self.pp)):
            if s > 1:
                names.append(n)
                sizes.append(s)
        if not names:          # the single-device degenerate plan
            names, sizes = ["dp"], [1]
        return tuple(names), tuple(sizes)

    @property
    def n_devices(self):
        return self.dp * self.mp * self.pp

    def describe(self):
        parts = [f"dp{self.dp}"]
        if self.mp > 1:
            parts.append(f"mp{self.mp}")
        if self.pp > 1:
            parts.append(f"pp{self.pp}x{self.n_micro}mb")
        if self.quantized_allreduce:
            parts.append("int8grad")
        if self.edge_compress:
            parts.append(f"edge_c{self.edge_compress}")
        if self.compress_grad_edge:
            parts.append("gradedge_c8")
        return "+".join(parts)

    def to_dict(self):
        return {"dp": self.dp, "mp": self.mp, "pp": self.pp,
                "n_micro": self.n_micro, "stage_layers": self.stage_layers,
                "quantized_allreduce": self.quantized_allreduce,
                "edge_compress": self.edge_compress,
                "describe": self.describe()}

    def __repr__(self):
        return f"Plan({self.describe()})"


class ModelProfile:
    """Trace-only cost profile of one bundled tiny model.

    ``trace()`` builds the dp=1 trainer (the same setup the sharding
    targets use), jit-LOWERS its step — no execution — and reads XLA's
    ``cost_analysis()`` for total step FLOPs / bytes accessed, scaled
    linearly from the trace batch to :data:`GLOBAL_BATCH`. Parameter /
    optimizer-state bytes and the quantized-reduce eligibility set
    (float params >= 1024 elements, the _resolve_compress rule) come
    from the constructed trainer's pytrees. The measured entry is
    recorded into the cost registry under ``site="plan"`` so
    ``trace.costs.table()`` shows what the planner priced.
    """

    __slots__ = ("name", "n_layers", "hidden", "seq", "vocab",
                 "step_flops", "step_bytes", "param_bytes", "opt_bytes",
                 "qar_eligible_bytes", "supports_pipeline", "supports_mp")

    def __init__(self, name, n_layers, hidden, seq, vocab, step_flops,
                 step_bytes, param_bytes, opt_bytes, qar_eligible_bytes,
                 supports_pipeline=False, supports_mp=False):
        self.name = name
        self.n_layers = int(n_layers)
        self.hidden = int(hidden)
        self.seq = int(seq)
        self.vocab = int(vocab)
        self.step_flops = float(step_flops)
        self.step_bytes = float(step_bytes)
        self.param_bytes = int(param_bytes)
        self.opt_bytes = int(opt_bytes)
        self.qar_eligible_bytes = int(qar_eligible_bytes)
        self.supports_pipeline = bool(supports_pipeline)
        self.supports_mp = bool(supports_mp)

    @classmethod
    def trace(cls, model_name):
        import jax
        import jax.numpy as jnp

        from ..core.generator import default_generator
        from ..trace import costs
        from .sharding_flow import _tiny_train_setup

        trainer, batch, _ = _tiny_train_setup(model_name, dp=1)
        step = trainer._build(list(batch))
        lr = jnp.asarray(trainer.optimizer.get_lr(), dtype=jnp.float32)
        key = default_generator().fold_in(0)
        lowered = jax.jit(step).lower(trainer.params, trainer.opt_state,
                                      trainer.buffers, lr, key, *batch)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):   # some backends: list of dicts
            merged = {}
            for d in ca or []:
                for k, v in d.items():
                    merged[k] = merged.get(k, 0.0) + float(v)
            ca = merged
        trace_batch = int(batch[0].shape[0])
        scale = GLOBAL_BATCH / float(trace_batch)
        step_flops = float(ca.get("flops", 0.0)) * scale
        step_bytes = float(ca.get("bytes accessed", 0.0)) * scale
        costs.record_manual("plan", f"{model_name}.step",
                            flops=step_flops, bytes_accessed=step_bytes)

        def _nbytes(tree):
            return sum(int(np.prod(v.shape)) * v.dtype.itemsize
                       for v in jax.tree_util.tree_leaves(tree)
                       if hasattr(v, "shape"))

        params = trainer.params
        param_bytes = _nbytes(params)
        opt_bytes = _nbytes(trainer.opt_state)
        eligible = sum(
            int(np.prod(v.shape)) * v.dtype.itemsize
            for v in params.values()
            if jnp.issubdtype(v.dtype, jnp.floating)
            and int(np.prod(v.shape)) >= 1024)
        layer = trainer.layer
        from ..distributed.split import collect_spmd_specs

        return cls(
            name=model_name,
            n_layers=2, hidden=64, seq=SEQ_LEN, vocab=256,
            step_flops=step_flops, step_bytes=step_bytes,
            param_bytes=param_bytes, opt_bytes=opt_bytes,
            qar_eligible_bytes=eligible,
            supports_pipeline=hasattr(layer, "pipeline_split"),
            supports_mp=bool(collect_spmd_specs(layer)))

    def to_dict(self):
        return {s: getattr(self, s) for s in self.__slots__}


class CostModel:
    """Price a :class:`Plan` against a :class:`ModelProfile`.

    All knobs are constructor parameters (no flags — the audit-facing
    budgets stay explicit): ``hbm_bytes`` the per-device HBM budget,
    ``peak`` / ``hbm_bw`` / ``net_bw`` the roofline denominators
    (default: trace.costs.peak_flops() and the nominal bandwidths —
    on the CPU harness only relative ordering matters).

    ``constants`` injects a MEASURED constants table
    (analysis/calibrate.py over perf-ledger rows; ``tools/plan_search.py
    --calibrated``): recognized keys ``peak_flops`` / ``hbm_bandwidth``
    / ``net_bandwidth`` override the corresponding denominator, so plan
    ranking prices against the hardware the ledger actually observed
    instead of the nominal tables. Explicit ``peak=``/``hbm_bw=``/
    ``net_bw=`` arguments still win — a caller pinning a denominator by
    hand outranks a recorded table.
    """

    def __init__(self, hbm_bytes=DEFAULT_HBM_BYTES, peak=None,
                 hbm_bw=None, net_bw=NOMINAL_NET_BW, constants=None):
        self.hbm_bytes = int(hbm_bytes)
        self._peak = peak
        self._hbm_bw = hbm_bw
        self.net_bw = float(net_bw)
        self.constants = dict(constants) if constants else None
        if self.constants:
            if peak is None and self.constants.get("peak_flops"):
                self._peak = float(self.constants["peak_flops"])
            if hbm_bw is None and self.constants.get("hbm_bandwidth"):
                self._hbm_bw = float(self.constants["hbm_bandwidth"])
            if net_bw == NOMINAL_NET_BW \
                    and self.constants.get("net_bandwidth"):
                self.net_bw = float(self.constants["net_bandwidth"])

    @property
    def peak(self):
        if self._peak is None:
            from ..trace import costs

            self._peak = float(costs.peak_flops())
        return self._peak

    @property
    def hbm_bw(self):
        if self._hbm_bw is None:
            from ..trace import costs

            self._hbm_bw = float(costs.peak_hbm_bandwidth())
        return self._hbm_bw

    # -- config sanity (the planner's OWN named rejections) -----------------
    def check_config(self, plan, profile, devices):
        """plan-invalid-config findings for configurations no analyzer
        gets a chance to see (nothing traceable exists to analyze)."""
        out = []

        def bad(msg):
            out.append(Finding("plan-invalid-config", "error", msg,
                               where=plan.describe()))

        if plan.dp < 1 or plan.mp < 1 or plan.pp < 1:
            bad(f"axis sizes must be >= 1, got dp={plan.dp} "
                f"mp={plan.mp} pp={plan.pp}")
            return out
        if GLOBAL_BATCH % plan.dp:
            bad(f"dp={plan.dp} does not divide the global batch "
                f"{GLOBAL_BATCH}")
        if plan.mp > 1 and not profile.supports_mp:
            bad(f"mp={plan.mp} but model '{profile.name}' declares no "
                "tensor-parallel param specs "
                "(distributed/split.collect_spmd_specs is empty) — the "
                "mp axis would replicate every parameter")
        if plan.pp > 1:
            if not profile.supports_pipeline:
                bad(f"pp={plan.pp} but model '{profile.name}' has no "
                    "pipeline_split()")
            if profile.n_layers % plan.pp:
                bad(f"pp={plan.pp} does not divide the {profile.n_layers}"
                    "-layer body into equal stages")
            if GLOBAL_BATCH % plan.n_micro:
                bad(f"n_micro={plan.n_micro} does not divide the global "
                    f"batch {GLOBAL_BATCH}")
            if plan.n_micro < plan.pp:
                bad(f"n_micro={plan.n_micro} < pp={plan.pp}: the "
                    "schedule cannot fill the pipeline")
        if plan.pp == 1 and (plan.edge_compress or plan.compress_grad_edge):
            bad("edge compression without a pipeline axis — there is no "
                "stage edge to compress")
        if plan.quantized_allreduce and plan.dp == 1:
            bad("quantized_allreduce with dp=1 — there is no gradient "
                "reduce to compress")
        if plan.quantized_allreduce and plan.mp > 1:
            bad("quantized_allreduce does not compose with tensor-"
                "parallel extra_param_specs (params must be replicated "
                "over dp — distributed/spmd.py _resolve_compress)")
        return out

    # -- memory -------------------------------------------------------------
    def memory_bytes(self, plan, profile):
        """Per-device HBM bytes, as (total, breakdown dict)."""
        state = (profile.param_bytes + profile.opt_bytes) / (
            plan.mp * plan.pp)
        boundary = (GLOBAL_BATCH / plan.dp) * profile.seq * \
            profile.hidden * 4
        if plan.pp > 1:
            mb_boundary = (GLOBAL_BATCH / plan.n_micro) * profile.seq * \
                profile.hidden * 4
            inflight = min(plan.pp, plan.n_micro)
            act = (profile.n_layers / plan.pp) * ACT_LIVE_FACTOR * \
                mb_boundary * inflight
        else:
            act = profile.n_layers * ACT_LIVE_FACTOR * boundary / plan.mp
        residual = profile.qar_eligible_bytes \
            if plan.quantized_allreduce else 0
        total = state + act + residual
        return total, {"state_bytes": state, "activation_bytes": act,
                       "qar_residual_bytes": residual}

    def check_memory(self, plan, profile):
        total, brk = self.memory_bytes(plan, profile)
        if total <= self.hbm_bytes:
            return []
        detail = ", ".join(f"{k}={v / (1 << 20):.1f}MiB"
                           for k, v in brk.items() if v)
        return [Finding(
            "plan-hbm-over-budget", "error",
            f"per-device HBM {total / (1 << 20):.1f} MiB exceeds the "
            f"{self.hbm_bytes / (1 << 20):.0f} MiB budget ({detail}) — "
            "raise dp/pp or shrink the per-device batch",
            where=plan.describe())]

    # -- communication ------------------------------------------------------
    def comm_terms(self, plan, profile, flow=None):
        """Per-device communication bytes by source, plus a message
        count for the latency term. ``flow`` is a
        sharding_flow.flow_summary dict of the plan's traced program
        class; when it carries measured collective bytes (the shard_map
        paths) those REPLACE the analytic dp-sync term."""
        terms = {"dp_sync_bytes": 0.0, "mp_sync_bytes": 0.0,
                 "edge_wire_bytes": 0.0, "measured": False}
        messages = 0

        measured = float((flow or {}).get("collective_bytes_total", 0.0))
        if plan.pp == 1 and measured > 0:
            # explicit collectives in the traced program (quantized
            # shard_map reduce): the analyzer's numbers win
            terms["dp_sync_bytes"] = measured
            terms["measured"] = True
            messages += sum((flow.get("collective_counts") or {}).values())
        elif plan.dp > 1:
            ring = 2.0 * (plan.dp - 1) / plan.dp
            grad = profile.param_bytes
            if plan.quantized_allreduce:
                elig = profile.qar_eligible_bytes
                wire = elig / int8_wire_ratio(QAR_BLOCK) + (grad - elig)
            else:
                wire = grad
            terms["dp_sync_bytes"] = ring * wire
            messages += 3 if plan.quantized_allreduce else 1

        if plan.mp > 1:
            act_dev = (GLOBAL_BATCH / plan.dp) * profile.seq * \
                profile.hidden * 4
            terms["mp_sync_bytes"] = 4 * profile.n_layers * \
                2.0 * (plan.mp - 1) / plan.mp * act_dev
            messages += 4 * profile.n_layers

        if plan.pp > 1:
            from . import handoff_schema

            mb = GLOBAL_BATCH // plan.n_micro
            dims = {"mb": mb, "t": profile.seq, "d": profile.hidden}
            fwd = handoff_schema.wire_bytes(
                "mpmd_activation", dims, compress=plan.edge_compress)
            bwd = handoff_schema.wire_bytes("mpmd_grad", dims)
            boundaries = plan.pp - 1
            terms["edge_wire_bytes"] = boundaries * plan.n_micro * \
                (fwd + bwd)
            messages += 2 * boundaries * plan.n_micro
            # the dp grad sync still applies inside each stage when the
            # plan carries both axes (not enumerated today, priced for
            # completeness) — pure-pp plans have per-stage params, no sync

        return terms, messages

    # -- the score ----------------------------------------------------------
    def score(self, plan, profile, flow=None):
        """Cost breakdown dict for one plan; ``total_s`` is the rank
        key (smaller wins). Never raises on a verified plan."""
        shards = plan.dp * plan.mp * plan.pp
        flops_dev = profile.step_flops / shards
        bytes_dev = profile.step_bytes / shards
        compute_s = max(flops_dev / self.peak, bytes_dev / self.hbm_bw)
        bubble = 1.0
        if plan.pp > 1:
            bubble = (plan.pp - 1 + plan.n_micro) / float(plan.n_micro)
        compute_s *= bubble

        terms, messages = self.comm_terms(plan, profile, flow=flow)
        comm_bytes = (terms["dp_sync_bytes"] + terms["mp_sync_bytes"] +
                      terms["edge_wire_bytes"])
        comm_s = comm_bytes / self.net_bw + messages * LINK_LATENCY_S

        mem, mem_brk = self.memory_bytes(plan, profile)
        out = {"plan": plan.to_dict(), "compute_s": compute_s,
               "bubble": bubble, "comm_s": comm_s,
               "comm_bytes": comm_bytes, "messages": messages,
               "mem_bytes_per_device": mem,
               "total_s": compute_s + comm_s,
               "terms": dict(terms, **mem_brk)}
        return out
