"""Builtin jaxpr analysis passes (the REGISTER_PASS battery).

Every pass is `fn(ctx) -> list[Finding]`, registered under a unique name
with a default severity. Passes only read the jaxpr — nothing is compiled
or executed — so the whole battery runs in milliseconds even over the
flagship model traces, cheap enough for the tier-1 gate.

Severity contract (pinned by tests/test_graph_lint_gate.py): the bundled
models and the serving decode step must produce ZERO error findings;
warnings are allowed and counted against tests/lint_baseline.json.
"""
import numpy as np

from .collectives import (HLO_COLLECTIVE_KINDS, count_hlo_collectives,
                          count_jaxpr_collectives,
                          count_quantized_collectives)
from .jaxpr_utils import fmt_aval, is_key_aval, iter_eqns, sub_jaxprs
from .registry import register_pass

# ---------------------------------------------------------------------------
# host-sync: callbacks block the device stream (device_get / .item()-shaped
# pulls raise at trace time and are policed by Tensor._to_host + the source
# linter; what CAN hide in a traced graph is a callback primitive).
# ---------------------------------------------------------------------------

_BLOCKING_CALLBACKS = {"pure_callback", "io_callback", "callback"}
_DEBUG_CALLBACKS = {"debug_callback", "debug_print"}


@register_pass("host-sync", severity="error")
def host_sync(ctx):
    out = []
    for eqn, path in iter_eqns(ctx.jaxpr):
        p = eqn.primitive.name
        if p in _BLOCKING_CALLBACKS:
            out.append(host_sync.finding(
                f"host callback '{p}' inside the traced graph: every step "
                "round-trips device->host->device (the .numpy()/.item() "
                "class of sync, compiled in)", where=path))
        elif p in _DEBUG_CALLBACKS:
            out.append(host_sync.finding(
                f"debug callback '{p}' in traced graph: fine for "
                "debugging, a host sync per step if left in a hot loop",
                where=path, severity="warning"))
    return out


# ---------------------------------------------------------------------------
# PRNG hygiene: key reuse + baked trace-time keys.
#
# Consuming the same key twice — by two samplers, OR by two splits (split
# is deterministic: split(k) twice yields identical subkeys) — means
# correlated randomness. Alias-producing eqns (slice/squeeze on a key
# array) are resolved to (root, selector) identities so the canonical
# dropout chain `split -> keys[0], keys[1]` does not false-positive while
# `keys[0], keys[0]` does.
# ---------------------------------------------------------------------------

_RANDOM_SINKS = {"random_bits", "threefry2x32", "random_gamma",
                 "rng_bit_generator"}
_KEY_DERIVERS = {"random_split", "random_fold_in"}   # consume key material
_KEY_ALIASES = {"copy", "device_put", "broadcast_in_dim", "reshape",
                "slice", "squeeze", "expand_dims", "transpose",
                "convert_element_type", "random_wrap", "random_unwrap",
                "dynamic_slice", "gather"}
_ALIAS_PARAM_KEYS = ("start_indices", "limit_indices", "strides",
                     "dimensions", "permutation", "new_sizes",
                     "slice_sizes", "broadcast_dimensions", "shape",
                     "dimension_numbers")


class _KeyFlow:
    """Per-jaxpr key-usage analysis with memoized recursion into calls."""

    def __init__(self):
        self.memo = {}       # id(jaxpr) -> set of materially-used invar idx
        self.findings = []   # [(sites,)] — each a reuse of one identity

    def _alias_id(self, producers, var, depth=0):
        from .jaxpr_utils import is_literal

        eqn = producers.get(id(var))
        if eqn is None or depth > 64:
            return id(var)
        if eqn.primitive.name in _KEY_ALIASES and eqn.invars and \
                hasattr(eqn.invars[0], "aval"):
            # a TRACED operand (dynamic_slice start, gather indices) makes
            # the selection value-dependent — two such slices may or may
            # not pick the same key, so each stays a DISTINCT identity
            # (conservative: misses reuse via equal traced indices, never
            # false-positives on keys[i] vs keys[j])
            if any(not is_literal(v) for v in eqn.invars[1:]):
                return id(var)
            sel = tuple((k, str(eqn.params[k])) for k in _ALIAS_PARAM_KEYS
                        if k in eqn.params)
            return (self._alias_id(producers, eqn.invars[0], depth + 1),
                    eqn.primitive.name, sel)
        return id(var)

    def analyze(self, jaxpr, path=""):
        """Returns the set of invar indices whose keys are materially
        consumed (directly or transitively); records reuse findings."""
        key = id(jaxpr)
        if key in self.memo:
            return self.memo[key]
        self.memo[key] = set()   # cycle guard (jaxprs are acyclic, but…)

        producers = {}
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                producers[id(ov)] = eqn
        # per alias identity: "direct" sink/split consumptions, and
        # fold_in consumptions bucketed by their fold operand. Folding
        # DISTINCT data into one key is the documented-safe idiom
        # (per-rank/per-phase fold_ins in distributed/compress.py);
        # everything else — two sinks, two splits, two fold_ins of the
        # SAME data, or a raw sink/split MIXED with any fold of the same
        # key — is correlated randomness and still flags.
        uses = {}   # alias identity -> {"direct": [...], "folds": {disc: [...]}}

        def use(var, where, prim, disc=None):
            ident = self._alias_id(producers, var)
            entry = uses.setdefault(ident, {"direct": [], "folds": {}})
            if disc is None:
                entry["direct"].append((where, prim))
            else:
                entry["folds"].setdefault(disc, []).append((where, prim))

        from .jaxpr_utils import is_literal

        def fold_disc(eqn):
            """random_fold_in's consumption bucket: the fold operand
            (literal value, or traced-var identity)."""
            parts = []
            for v in eqn.invars:
                if hasattr(v, "aval") and is_key_aval(v.aval):
                    continue
                parts.append(str(v.val) if is_literal(v) else id(v))
            return tuple(parts)

        for i, eqn in enumerate(jaxpr.eqns):
            here = f"{path}eqns[{i}]"
            p = eqn.primitive.name
            if p in _RANDOM_SINKS or p in _KEY_DERIVERS:
                disc = fold_disc(eqn) if p == "random_fold_in" else None
                for v in eqn.invars:
                    if hasattr(v, "aval") and is_key_aval(v.aval):
                        use(v, here, p, disc)
                continue
            subs = [s for _, s in sub_jaxprs(eqn)]
            if subs:
                tag = eqn.params.get("name", "")
                label = f"{p}:{tag}" if tag else p
                for sub in subs:
                    inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                    used_idx = self.analyze(inner, f"{here}/{label}/")
                    # align inner invars to the eqn's trailing invars
                    # (cond carries a leading predicate, scan leading
                    # consts — tail alignment covers both)
                    off = len(eqn.invars) - len(inner.invars)
                    for idx in used_idx:
                        j = idx + off
                        if 0 <= j < len(eqn.invars):
                            v = eqn.invars[j]
                            if hasattr(v, "aval") and is_key_aval(v.aval):
                                use(v, here, label)

        invar_ids = {id(v): i for i, v in enumerate(jaxpr.invars)}
        used_invars = set()
        for ident, entry in uses.items():
            root = ident
            while isinstance(root, tuple):
                root = root[0]
            if root in invar_ids:
                used_invars.add(invar_ids[root])
            direct, folds = entry["direct"], entry["folds"]
            # memoization => each condition reported once per jaxpr,
            # and ONE finding per reused alias identity
            if len(direct) >= 2:
                self.findings.append((direct,))
            for sites in folds.values():
                if len(sites) >= 2:
                    self.findings.append((sites,))
            if len(direct) == 1 and folds:
                # raw consumption + fold(s) of the SAME key: the sink's
                # stream is correlated with every folded child stream
                # (one representative site per fold bucket; the >=2
                # direct case already reported this alias above)
                self.findings.append(
                    (direct + [s[0] for s in folds.values()],))
        self.memo[key] = used_invars
        return used_invars


@register_pass("prng-key-reuse", severity="error")
def prng_key_reuse(ctx):
    flow = _KeyFlow()
    flow.analyze(ctx.jaxpr)
    out = []
    for (sites,) in flow.findings:
        where = sites[0][0]
        consumers = ", ".join(f"{prim} @ {p}" for p, prim in sites[:4])
        out.append(prng_key_reuse.finding(
            f"PRNG key consumed {len(sites)}x — identical key material "
            f"feeds [{consumers}]; split the key per consumer "
            "(jax.random.split) or fold_in distinct data", where=where))
    return out


@register_pass("prng-const-key", severity="warning")
def prng_const_key(ctx):
    """A key baked as a trace-time constant: every invocation of the
    compiled program replays the SAME randomness (the generator.py
    docstring's stale-dropout-mask hazard, detected statically)."""
    const_ids = {id(cv): i for i, cv in enumerate(ctx.jaxpr.constvars)
                 if is_key_aval(cv.aval)}
    if not const_ids:
        return []
    # constvars are scoped to the top level — one finding per (key, site)
    consumed = set()
    for eqn, path in iter_eqns(ctx.jaxpr, max_depth=0):
        for v in eqn.invars:
            if id(v) in const_ids:
                consumed.add((const_ids[id(v)], path))
    out = []
    for idx, path in sorted(consumed):
        out.append(prng_const_key.finding(
            "PRNG key baked into the trace as a constant: the compiled "
            "program reuses identical randomness every call (draw keys "
            "inside a traced_rng scope or thread them as arguments)",
            where=path))
    return out


# ---------------------------------------------------------------------------
# dtype-promotion audit: silent widening costs 2x bytes (f32->f64 also
# 10-100x FLOPs on TPU, which has no f64 units).
# ---------------------------------------------------------------------------

_WIDENINGS = {  # (src, dst) -> severity
    ("float32", "float64"): "error",
    ("bfloat16", "float32"): "warning",
    ("float16", "float32"): "warning",
    ("int32", "int64"): "warning",
}


@register_pass("dtype-promotion", severity="warning")
def dtype_promotion(ctx):
    groups = {}   # (src, dst) -> [paths]
    for eqn, path in iter_eqns(ctx.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        v = eqn.invars[0]
        if not hasattr(v, "aval"):
            continue
        if getattr(v.aval, "weak_type", False):
            continue   # python-scalar promotion, not a data widening
        pair = (str(v.aval.dtype), str(np.dtype(eqn.params["new_dtype"])))
        if pair in _WIDENINGS:
            groups.setdefault(pair, []).append(path)
    out = []
    for (src, dst), paths in sorted(groups.items()):
        sev = _WIDENINGS[(src, dst)]
        ex = "; ".join(paths[:3]) + ("; …" if len(paths) > 3 else "")
        out.append(dtype_promotion.finding(
            f"silent {src}->{dst} widening x{len(paths)} (examples: {ex}) "
            "— 2x bytes moved per widened tensor"
            + ("; f64 has no TPU unit" if dst == "float64" else ""),
            where=paths[0], severity=sev))
    return out


# ---------------------------------------------------------------------------
# dead-code report: eqns whose outputs nothing consumes. XLA DCEs them at
# compile time, but tracing/lowering them still costs, and dead regions
# usually mean a model wiring bug (an output computed and dropped).
# ---------------------------------------------------------------------------


def _dead_eqns(jaxpr, path=""):
    import jax

    live = {id(v) for v in jaxpr.outvars if hasattr(v, "aval")}
    dead = []
    for i in range(len(jaxpr.eqns) - 1, -1, -1):
        eqn = jaxpr.eqns[i]
        outs_alive = bool(eqn.effects) or any(
            not isinstance(v, jax.core.DropVar) and id(v) in live
            for v in eqn.outvars)
        if outs_alive:
            for v in eqn.invars:
                if hasattr(v, "aval"):
                    live.add(id(v))
        else:
            dead.append((f"{path}eqns[{i}]", eqn.primitive.name,
                         fmt_aval(eqn.outvars[0].aval)
                         if eqn.outvars else ""))
    for i, eqn in enumerate(jaxpr.eqns):
        tag = eqn.params.get("name", "")
        label = (f"{eqn.primitive.name}:{tag}" if tag
                 else eqn.primitive.name)
        for _, sub in sub_jaxprs(eqn):
            inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            dead.extend(_dead_eqns(inner, f"{path}eqns[{i}]/{label}/"))
    return dead


@register_pass("dead-code", severity="info")
def dead_code(ctx):
    dead = _dead_eqns(ctx.jaxpr)
    if not dead:
        return []
    total = sum(1 for _ in iter_eqns(ctx.jaxpr))
    ex = ", ".join(f"{prim}@{p}" for p, prim, _ in dead[:4])
    sev = "warning" if len(dead) * 4 > total else "info"
    return [dead_code.finding(
        f"{len(dead)}/{total} eqns compute values nothing consumes "
        f"(examples: {ex}) — XLA will DCE them, but dead regions usually "
        "mean a dropped output or stale wiring", where=dead[0][0],
        severity=sev)]


# ---------------------------------------------------------------------------
# recompilation-hazard scan: python scalars / arrays closed over as consts.
# A const that varies per call (a step count, a freshly-drawn array) means
# a new trace+compile per call — the classic silent-recompile bug.
# ---------------------------------------------------------------------------


@register_pass("recompile-hazard", severity="info")
def recompile_hazard(ctx):
    out = []
    scalars = []
    for cv, c in zip(ctx.jaxpr.constvars, ctx.consts):
        if is_key_aval(cv.aval):
            continue   # prng-const-key owns baked keys
        size = int(np.prod(getattr(cv.aval, "shape", ()) or (1,)))
        if getattr(cv.aval, "shape", None) == ():
            scalars.append(fmt_aval(cv.aval))
        elif size >= ctx.large_threshold:
            out.append(recompile_hazard.finding(
                f"large array ({fmt_aval(cv.aval)}, {size} elems) closed "
                "over as a trace constant — baked into the executable "
                "(weights should flow as arguments; a varying closure "
                "forces a recompile per distinct value)",
                where="constvars", severity="warning"))
    if scalars:
        out.append(recompile_hazard.finding(
            f"{len(scalars)} python scalar(s) baked as trace constants "
            f"({', '.join(scalars[:6])}) — if any varies across calls, "
            "each new value re-traces and re-compiles the program",
            where="constvars"))
    return out


# ---------------------------------------------------------------------------
# collective-count audit: the EQuARX-motivated collective-stream ledger.
# ---------------------------------------------------------------------------


@register_pass("collective-count", severity="info")
def collective_count(ctx):
    out = []
    jx = count_jaxpr_collectives(ctx.jaxpr)
    for fam in sorted(jx):
        out.append(collective_count.finding(
            f"{jx[fam]} {fam} collective(s) in the traced graph",
            where=fam))
    quant = {k: v for k, v in count_quantized_collectives(ctx.jaxpr).items()
             if v}
    if quant:
        out.append(collective_count.finding(
            f"quantized reduce family (int8 wire): {quant} — the "
            "reduce-scatter/all-gather pair of a wire-compressed "
            "all-reduce (distributed/compress.py, docs/DISTRIBUTED.md); "
            "their payload bytes are collective_bytes_total wire bytes, "
            "not the dequantized fp32 size", where="quantized"))
    if ctx.hlo_text is not None:
        # count every family the jaxpr side knows, not just the 3 kinds
        # the perf-budget recording format defaults to
        hlo = count_hlo_collectives(ctx.hlo_text,
                                    kinds=HLO_COLLECTIVE_KINDS)
        present = {k: v for k, v in hlo.items() if v}
        if present:
            out.append(collective_count.finding(
                f"post-partitioning HLO collective counts: {present} "
                "(exact — the perf-budget gate pins these per program)",
                where="hlo"))
    return out


# ---------------------------------------------------------------------------
# (unsharded-large-tensor moved to sharding_flow.py as the spec-propagating
# `implicit-replication` pass — ISSUE 13 upgraded the size-only heuristic
# into provenance-chained replication analysis.)


# ---------------------------------------------------------------------------
# donation-miss: an input whose shape/dtype matches an output could be
# donated (aliased in place) — not donating doubles its HBM footprint.
# ---------------------------------------------------------------------------


@register_pass("donation-miss", severity="info")
def donation_miss(ctx):
    outs = {}
    for ov in ctx.jaxpr.outvars:
        if hasattr(ov, "aval") and getattr(ov.aval, "shape", None) is not None:
            outs.setdefault(
                (tuple(ov.aval.shape), str(ov.aval.dtype)), 0)
            outs[(tuple(ov.aval.shape), str(ov.aval.dtype))] += 1
    missed = []
    for i, iv in enumerate(ctx.jaxpr.invars):
        if ctx.donated is not None and i in ctx.donated:
            continue
        aval = getattr(iv, "aval", None)
        if aval is None or not getattr(aval, "shape", None):
            continue
        size = int(np.prod(aval.shape))
        key = (tuple(aval.shape), str(aval.dtype))
        if size >= ctx.large_threshold and outs.get(key, 0) > 0:
            missed.append((i, fmt_aval(aval)))
    if not missed:
        return []
    sev = "warning" if ctx.donated is not None else "info"
    ex = ", ".join(f"invar[{i}] {a}" for i, a in missed[:4])
    return [donation_miss.finding(
        f"{len(missed)} large input(s) whose shape/dtype matches an "
        f"output are not donated ({ex}) — donate_argnums would let XLA "
        "reuse the buffer in place (2x HBM otherwise)",
        where=f"invar[{missed[0][0]}]", severity=sev)]
