"""One shared ``# lint: allow(...)`` vocabulary for every static pass.

Before ISSUE 12 each analysis surface grew its own suppression parsing
(source_lint carried the regex + a private alias table); the contract
auditor adds three more AST passes that all need the same escape hatch,
so the marker grammar, the alias table, and the lookup live here once.

A suppression is a trailing comment on the offending line::

    rng = np.random.RandomState(seed)  # lint: allow(np-random-in-traced-code)

Markers accept either the full rule name or any registered shorthand
alias (e.g. ``client_output`` for ``nonreduced-client-output``).
``tools/contract_audit.py --list-rules`` and ``tools/graph_lint.py
--list-rules`` print every rule with its accepted spellings so the
escape is discoverable without reading this file.
"""
import re

__all__ = ["ALLOW_RE", "RULE_ALIASES", "allowed", "spellings"]

ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_,\- ]+)\)")

#: rule -> shorthand marker spellings accepted alongside the full name.
#: ONE table for source_lint AND the contract-auditor passes — a new
#: pass registers its aliases here, never in a private copy.
RULE_ALIASES = {
    "nonreduced-client-output": ("client_output",),
    "orphan-flag-unread": ("orphan-flag",),
    "orphan-flag-undefined": ("orphan-flag",),
    "lazy-module-leak": ("lazy-import", "eager-import"),
    "unlocked-thread-shared-write": ("thread-shared-write",),
    "hot-path-flag-read": ("hot-flag-read",),
    "metric-undocumented": ("undocumented-metric",),
    "span-undocumented": ("undocumented-span",),
    # ISSUE 13: sharding-flow / transfer-edge / kernel-budget rules
    "implicit-replication": ("replicated-tensor",),
    "resharding-churn": ("reshard-churn",),
    "collective-axis-mismatch": ("bad-collective-axis",),
    "ppermute-malformed": ("bad-ppermute",),
    "branch-collective-mismatch": ("branch-collectives",),
    "handoff-schema-drift": ("handoff-drift",),
    "kernel-vmem-over-budget": ("vmem-budget",),
    "kernel-low-precision-accumulator": ("int8-accumulator",),
    # ISSUE 16: auto-parallel plan-search rules (cost_model/plan_search)
    "plan-invalid-config": ("bad-plan",),
    "plan-hbm-over-budget": ("hbm-budget",),
    "plan-handoff-mismatch": ("plan-handoff",),
    "plan-space-empty": ("empty-plan-space",),
    # ISSUE 17: measured-constant calibration rules (analysis/calibrate)
    "calib-insufficient-rows": ("calib-rows",),
    "calib-no-signal": ("calib-signal",),
    "calib-fit-unstable": ("calib-unstable",),
}


def spellings(rule):
    """Every marker spelling that suppresses `rule` (full name first)."""
    return (rule,) + tuple(RULE_ALIASES.get(rule, ()))


def allowed(lines, lineno, rule):
    """True when line `lineno` (1-based) of `lines` carries an allow
    marker naming `rule` (or one of its aliases)."""
    if 1 <= lineno <= len(lines):
        m = ALLOW_RE.search(lines[lineno - 1])
        if m:
            names = [r.strip() for r in m.group(1).split(",")]
            return any(s in names for s in spellings(rule))
    return False
