"""Typed transfer edges: declared, statically audited handoff schemas.

The repo has four cross-program hand-offs whose payload layout was, until
ISSUE 13, an implicit contract between a producer and a consumer that
only broke at runtime (or silently corrupted a KV cache):

- ``disagg_kv`` — the prefill→decode KV handoff
  (``PrefillWorker.prefill`` → ``ServingEngine.admit_prefilled``);
- ``pipeline_stage`` — the stage-boundary activation a ppermute ring
  carries between pipeline ranks;
- ``federated_adapter`` — the flattened trainable-delta payload a
  federated client returns for aggregation;
- ``checkpoint_state`` — the {params, opt_state, step, lr} tree
  ``gather_train_state`` writes and ``restore_train_state`` re-places.

Each edge is declared ONCE, as a module-level **literal** dict named in
:data:`EDGES` (e.g. ``serving/disagg.py HANDOFF_SCHEMA``). Literal
matters: this module AST-extracts the declaration without importing the
declaring module, so the audit sees exactly what the runtime consumes —
one source of truth, checked from both sides:

- statically: ``audit_package()`` extracts every declaration, verifies
  the producer/consumer sites exist (and reference the schema where
  ``runtime_checked``), checks payload well-formedness, and pins each
  edge's fingerprint against ``tests/handoff_baseline.json`` — a silent
  KV-layout or payload drift fails lint before it corrupts a handoff;
- at runtime: consumers call :func:`validate` with the SAME declaration
  (``ServingEngine.admit_prefilled``, the pipeline trainer's stage-edge
  check) so a malformed payload raises naming the offending leaf.

Payload grammar — a dict of leaf specs (nesting allowed)::

    {"kc": {"shape": ("L", 1, "KVh", "T", "hd"), "dtype": "$cache",
            "layout": "[L, B, KVh, T, hd]", "quantizable": True}}

``shape`` entries are ints, symbolic dim names (bound via ``dims=`` at
validation, or on first use — consistency is still enforced), or the
``"..."`` wildcard (any trailing dims). ``dtype`` is a numpy dtype name
or a ``$name`` symbol bound via ``dtypes=``. ``quantizable`` leaves
accept a ``(values, scales)`` pair in place of the dense array (the
int8/fp8 KV-cache codec). CLI: ``python tools/contract_audit.py
--handoff`` (``--record`` stamps the baseline). See docs/ANALYSIS.md
"Declaring a transfer edge".
"""
import ast
import json
import os

from .registry import Finding

RULES = {
    "handoff-schema-missing": "error",
    "handoff-schema-malformed": "error",
    "handoff-site-unwired": "error",
    "handoff-schema-drift": "error",
    "handoff-schema-unpinned": "error",
    "handoff-baseline-stale": "error",
}

#: edge name -> (repo-relative declaring file, module-level attr). A new
#: cross-program hand-off registers here AND declares the literal; the
#: audit fails on either half alone.
EDGES = {
    "disagg_kv": ("paddle_tpu/serving/disagg.py", "HANDOFF_SCHEMA"),
    "kv_page_admit": ("paddle_tpu/serving/paging.py", "HANDOFF_SCHEMA"),
    "pipeline_stage": ("paddle_tpu/distributed/pipeline.py",
                       "HANDOFF_SCHEMA"),
    "federated_adapter": ("paddle_tpu/federated/averaging.py",
                          "HANDOFF_SCHEMA"),
    "checkpoint_state": ("paddle_tpu/distributed/spmd.py",
                         "CHECKPOINT_SCHEMA"),
    "mpmd_activation": ("paddle_tpu/distributed/stage.py",
                        "HANDOFF_SCHEMA"),
    "mpmd_grad": ("paddle_tpu/distributed/stage.py",
                  "HANDOFF_SCHEMA_GRAD"),
}

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "handoff_baseline.json")

_REQUIRED_KEYS = ("edge", "payload", "producer", "consumer")


def _pkg_root():
    """Directory containing the paddle_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# runtime validation (the consumer half)
# ---------------------------------------------------------------------------


class HandoffMismatch(ValueError):
    """A payload that does not match its edge's declared schema; the
    message names the edge, the leaf, and the field that diverged."""


def _is_leaf_spec(node):
    return isinstance(node, dict) and ("shape" in node or "dtype" in node
                                       or "kind" in node)


def _leaves(payload, prefix=""):
    for k in sorted(payload):
        v = payload[k]
        path = f"{prefix}{k}"
        if _is_leaf_spec(v):
            yield path, v
        elif isinstance(v, dict):
            yield from _leaves(v, f"{path}.")
        else:
            yield path, {"malformed": v}


def _check_shape(edge, leaf, declared, actual, binds):
    decl = list(declared)
    act = list(actual)
    if decl and decl[-1] == "...":
        decl = decl[:-1]
        if len(act) < len(decl):
            raise HandoffMismatch(
                f"[{edge}] {leaf}: rank {len(act)} < the declared "
                f"{len(decl)} leading dim(s) {tuple(declared)}")
        act = act[:len(decl)]
    elif len(decl) != len(act):
        raise HandoffMismatch(
            f"[{edge}] {leaf}: rank {len(act)} != declared rank "
            f"{len(decl)} ({tuple(declared)} vs {tuple(actual)})")
    for i, (d, a) in enumerate(zip(decl, act)):
        if d == "...":
            return
        if isinstance(d, int):
            if int(a) != d:
                raise HandoffMismatch(
                    f"[{edge}] {leaf}: dim[{i}] is {a}, declared {d}")
        else:
            want = binds.setdefault(str(d), int(a))
            if int(a) != want:
                raise HandoffMismatch(
                    f"[{edge}] {leaf}: dim[{i}] ('{d}') is {a}, but "
                    f"'{d}' is bound to {want} elsewhere in this payload")


def _check_dtype(edge, leaf, declared, actual, dtypes):
    want = declared
    if isinstance(want, str) and want.startswith("$"):
        want = (dtypes or {}).get(want[1:])
        if want is None:
            return   # unbound dtype symbol: structural check only
    if str(actual) != str(want):
        raise HandoffMismatch(
            f"[{edge}] {leaf}: dtype {actual}, declared {want}")


def validate(schema, values, dims=None, dtypes=None):
    """Check a payload against its declared schema.

    ``values`` maps leaf names (nested dicts allowed) to arrays — or to
    ``(values, scales)`` pairs for ``quantizable`` leaves. ``dims`` binds
    symbolic dim names ({"L": 2, "T": 64, ...}); unbound symbols bind on
    first use and must then agree across leaves. ``dtypes`` binds
    ``$name`` dtype symbols. Raises :class:`HandoffMismatch` naming the
    edge, leaf and field; returns the final symbol bindings.
    """
    edge = schema.get("edge", "?")
    binds = dict(dims or {})
    for leaf, spec in _leaves(schema["payload"]):
        if "malformed" in spec:
            raise HandoffMismatch(
                f"[{edge}] {leaf}: malformed leaf spec {spec['malformed']!r}")
        if spec.get("kind") == "opaque" or "shape" not in spec:
            continue   # structural-only leaves (checkpoint trees)
        node = values
        for part in leaf.split("."):
            if not isinstance(node, dict) or part not in node:
                raise HandoffMismatch(
                    f"[{edge}] payload is missing leaf '{leaf}'")
            node = node[part]
        if spec.get("quantizable") and isinstance(node, tuple):
            if len(node) != 2:
                raise HandoffMismatch(
                    f"[{edge}] {leaf}: quantized side must be a "
                    f"(values, scales) pair, got a {len(node)}-tuple")
            vals, scales = node
            _check_shape(edge, f"{leaf}.values", spec["shape"],
                         vals.shape, binds)
            if "dtype" in spec:
                # the quantized side's VALUES dtype honors the same
                # declaration the dense side does (a producer built with
                # a different cache codec must fail here, not corrupt
                # the consumer's cache on the row copy)
                _check_dtype(edge, f"{leaf}.values", spec["dtype"],
                             vals.dtype, dtypes)
            scale_shape = tuple(spec["shape"][:-1]) + (1,)
            _check_shape(edge, f"{leaf}.scales", scale_shape,
                         scales.shape, binds)
            _check_dtype(edge, f"{leaf}.scales", "float32", scales.dtype,
                         dtypes)
            continue
        if isinstance(node, tuple):
            raise HandoffMismatch(
                f"[{edge}] {leaf}: got a tuple where a plain array is "
                "declared (quantized row handed to a dense-cache engine?)")
        shape = getattr(node, "shape", None)
        if shape is None:
            raise HandoffMismatch(
                f"[{edge}] {leaf}: expected an array, got "
                f"{type(node).__name__}")
        _check_shape(edge, leaf, spec["shape"], shape, binds)
        if "dtype" in spec:
            _check_dtype(edge, leaf, spec["dtype"],
                         getattr(node, "dtype", "?"), dtypes)
    return binds


_DECL_CACHE = {}


def _declaration(edge):
    """The AST-extracted declaration for an EDGES name (cached — the
    plan-search cost model calls this per candidate)."""
    if edge not in _DECL_CACHE:
        if edge not in EDGES:
            raise ValueError(f"unknown edge {edge!r}; "
                             f"declared edges: {sorted(EDGES)}")
        _DECL_CACHE[edge] = extract_declaration(*EDGES[edge])
    return _DECL_CACHE[edge]


def wire_bytes(edge, dims, compress=None, dtypes=None):
    """Bytes one payload crossing `edge` puts on the wire.

    `edge` is an :data:`EDGES` name (or a schema dict); `dims` binds
    every symbolic dim. ``compress=None`` prices the dense payload at
    the declared dtype (``$sym`` dtypes resolve via `dtypes`, default
    float32 — the repo's training activation dtype); ``compress=8``
    prices ``quantizable`` leaves under the row codec — int8 values
    plus one float32 scale per row of the minor dim, the
    ``4 / (1 + 4/D)`` wire ratio over float32 that
    distributed/stage.py's StageEdge measures. Non-quantizable leaves
    stay dense either way (the schema, not the caller, decides what may
    shrink). Raises ValueError on unbound dims, wildcard shapes, or
    opaque leaves — a wire-byte count needs a concrete payload."""
    if compress not in (None, 8):
        raise ValueError(f"compress must be None or 8, got {compress!r}")
    schema = _declaration(edge) if isinstance(edge, str) else edge
    name = schema.get("edge", "?")
    binds = dict(dims or {})
    total = 0
    for leaf, spec in _leaves(schema["payload"]):
        if "malformed" in spec:
            raise ValueError(
                f"[{name}] {leaf}: malformed leaf spec "
                f"{spec['malformed']!r}")
        if spec.get("kind") == "opaque" or "shape" not in spec:
            raise ValueError(
                f"[{name}] {leaf}: opaque/shapeless leaf has no "
                "statically computable wire size")
        shape = []
        for i, d in enumerate(spec["shape"]):
            if d == "...":
                raise ValueError(
                    f"[{name}] {leaf}: wildcard dim[{i}] — bind a "
                    "concrete payload shape to price it")
            if isinstance(d, int):
                shape.append(d)
            elif str(d) in binds:
                shape.append(int(binds[str(d)]))
            else:
                raise ValueError(
                    f"[{name}] {leaf}: unbound dim '{d}' — pass it in "
                    "dims=")
        n = 1
        for d in shape:
            n *= d
        declared = spec.get("dtype", "float32")
        if isinstance(declared, str) and declared.startswith("$"):
            declared = (dtypes or {}).get(declared[1:], "float32")
        import numpy as _np

        itemsize = _np.dtype(str(declared)).itemsize
        if compress and spec.get("quantizable"):
            rows = n // shape[-1] if shape else 0
            total += n * 1 + rows * 4
        else:
            total += n * itemsize
    return total


# ---------------------------------------------------------------------------
# static extraction + fingerprinting (the audit half)
# ---------------------------------------------------------------------------


def extract_declaration(relpath, attr, pkg_root=None):
    """AST-extract the literal ``attr = {...}`` declaration from a file
    WITHOUT importing it. Returns the dict, or raises ValueError."""
    path = os.path.join(pkg_root or _pkg_root(), relpath)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=relpath)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if attr in targets:
            try:
                return ast.literal_eval(node.value)
            except (ValueError, SyntaxError) as exc:
                raise ValueError(
                    f"{relpath}: {attr} must be a pure literal (the "
                    f"static audit and the runtime consumer must read "
                    f"the same bytes): {exc}") from None
    raise ValueError(f"{relpath}: no module-level literal {attr} found")


def fingerprint(schema):
    """Canonical, diff-stable form of an edge declaration: the payload,
    the producer/consumer wiring, AND the runtime_checked bit (dropping
    a consumer's runtime validation is drift too) — doc prose excluded."""
    def canon(v):
        if isinstance(v, dict):
            return {k: canon(v[k]) for k in sorted(v)}
        if isinstance(v, (list, tuple)):
            return [canon(x) for x in v]
        return v

    keys = _REQUIRED_KEYS + ("runtime_checked",)
    return canon({k: schema[k] for k in keys if k in schema})


def _find_def(tree, dotted):
    """Locate 'fn' or 'Class.method' in a parsed module; returns the
    (start, end) line span or None."""
    parts = dotted.split(".")
    body = tree.body
    node = None
    for i, part in enumerate(parts):
        node = next(
            (n for n in body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and n.name == part), None)
        if node is None:
            return None
        body = getattr(node, "body", [])
    return (node.lineno, getattr(node, "end_lineno", node.lineno))


def _site_check(edge, role, site, attr, runtime_checked, pkg_root):
    """A site spec 'path/to/file.py::Qual.name' must exist; a
    runtime-checked edge's file must reference the schema attr."""
    out = []
    try:
        relpath, dotted = site.split("::", 1)
    except ValueError:
        return [Finding("handoff-site-unwired", "error",
                        f"[{edge}] {role} site {site!r} is not "
                        "'relpath.py::Qual.name'", where=site)]
    path = os.path.join(pkg_root, relpath)
    if not os.path.exists(path):
        return [Finding("handoff-site-unwired", "error",
                        f"[{edge}] {role} file {relpath} does not exist",
                        where=site)]
    with open(path, encoding="utf-8") as f:
        src = f.read()
    span = _find_def(ast.parse(src, filename=relpath), dotted)
    if span is None:
        out.append(Finding(
            "handoff-site-unwired", "error",
            f"[{edge}] {role} '{dotted}' not found in {relpath} — the "
            "declaration points at a site that no longer exists",
            where=site))
    elif runtime_checked and role == "consumer" and attr not in src:
        out.append(Finding(
            "handoff-site-unwired", "error",
            f"[{edge}] consumer file {relpath} never references "
            f"{attr} — the runtime validation is supposed to consume "
            "the same declaration the audit extracts", where=site))
    return out


def load_declarations(pkg_root=None):
    """{edge: schema-dict} for every registered edge, plus extraction
    findings for the ones that fail."""
    root = pkg_root or _pkg_root()
    decls, findings = {}, []
    for edge, (relpath, attr) in sorted(EDGES.items()):
        try:
            decl = extract_declaration(relpath, attr, pkg_root=root)
        except (ValueError, OSError) as exc:
            findings.append(Finding(
                "handoff-schema-missing", "error",
                f"[{edge}] {exc}", where=f"{relpath}::{attr}"))
            continue
        decls[edge] = decl
    return decls, findings


def _well_formed(edge, decl, relpath, attr):
    out = []
    where = f"{relpath}::{attr}"
    missing = [k for k in _REQUIRED_KEYS if k not in decl]
    if missing:
        out.append(Finding(
            "handoff-schema-malformed", "error",
            f"[{edge}] declaration lacks {missing}", where=where))
        return out
    if decl["edge"] != edge:
        out.append(Finding(
            "handoff-schema-malformed", "error",
            f"[{edge}] declaration names edge {decl['edge']!r} but is "
            f"registered as {edge!r}", where=where))
    for leaf, spec in _leaves(decl["payload"]):
        if "malformed" in spec:
            out.append(Finding(
                "handoff-schema-malformed", "error",
                f"[{edge}] payload leaf '{leaf}' is not a leaf spec: "
                f"{spec['malformed']!r}", where=where))
            continue
        shape = spec.get("shape")
        if shape is not None:
            bad = [d for d in shape
                   if not isinstance(d, int) and not isinstance(d, str)]
            if bad:
                out.append(Finding(
                    "handoff-schema-malformed", "error",
                    f"[{edge}] {leaf}: shape entries must be ints or "
                    f"symbolic names, got {bad}", where=where))
    return out


def check_baseline(decls, baseline):
    """Drift findings: every declared edge must be pinned with an equal
    fingerprint, and the baseline must not name edges that are gone."""
    out = []
    pinned = (baseline or {}).get("edges", {})
    for edge, decl in sorted(decls.items()):
        want = pinned.get(edge)
        got = fingerprint(decl)
        if want is None:
            out.append(Finding(
                "handoff-schema-unpinned", "error",
                f"[{edge}] edge is not in the recorded baseline — stamp "
                "it with `python tools/contract_audit.py --record` (a "
                "NEW transfer edge is an intentional act)", where=edge))
        elif want != got:
            diffs = _diff_fingerprints(want, got)
            out.append(Finding(
                "handoff-schema-drift", "error",
                f"[{edge}] declared schema drifted from the recorded "
                f"baseline ({'; '.join(diffs[:4])}) — a consumer built "
                "against the recorded layout would mis-read this "
                "payload; re-record ONLY if every side moved together",
                where=edge))
    for edge in sorted(set(pinned) - set(decls)):
        out.append(Finding(
            "handoff-baseline-stale", "error",
            f"[{edge}] baseline pins an edge that is no longer "
            "declared — remove it via --record", where=edge))
    return out


def _diff_fingerprints(want, got, prefix=""):
    diffs = []
    if isinstance(want, dict) and isinstance(got, dict):
        for k in sorted(set(want) | set(got)):
            if k not in want:
                diffs.append(f"{prefix}{k}: added")
            elif k not in got:
                diffs.append(f"{prefix}{k}: removed")
            elif want[k] != got[k]:
                diffs.extend(_diff_fingerprints(want[k], got[k],
                                                f"{prefix}{k}."))
    elif want != got:
        diffs.append(f"{prefix[:-1] or 'value'}: {want!r} -> {got!r}")
    return diffs


def record_baseline(path=None, pkg_root=None):
    """Stamp every extractable edge's fingerprint; returns the baseline
    dict (the contract_audit --record entry point)."""
    decls, findings = load_declarations(pkg_root=pkg_root)
    bad = [f for f in findings]
    if bad:
        raise ValueError(
            "cannot record a baseline over broken declarations: "
            + "; ".join(f.message for f in bad))
    base = {"edges": {e: fingerprint(d) for e, d in sorted(decls.items())}}
    with open(path or BASELINE_PATH, "w") as f:
        json.dump(base, f, indent=1, sort_keys=True)
        f.write("\n")
    return base


def audit_package(pkg_root=None, baseline_path=None):
    """The full handoff audit: extraction + well-formedness + site wiring
    + baseline drift. Returns a list of Findings."""
    root = pkg_root or _pkg_root()
    decls, findings = load_declarations(pkg_root=root)
    for edge, decl in sorted(decls.items()):
        relpath, attr = EDGES[edge]
        fs = _well_formed(edge, decl, relpath, attr)
        findings.extend(fs)
        if fs:
            continue
        rc = bool(decl.get("runtime_checked"))
        findings.extend(_site_check(edge, "producer", decl["producer"],
                                    attr, rc, root))
        findings.extend(_site_check(edge, "consumer", decl["consumer"],
                                    attr, rc, root))
    bpath = baseline_path or BASELINE_PATH
    if os.path.exists(bpath):
        with open(bpath) as f:
            baseline = json.load(f)
    else:
        baseline = None
        findings.append(Finding(
            "handoff-schema-unpinned", "error",
            f"no recorded baseline at {bpath} — run `python "
            "tools/contract_audit.py --record`", where=bpath))
    if baseline is not None:
        findings.extend(check_baseline(decls, baseline))
    return findings
