"""Jaxpr traversal helpers shared by the analysis passes.

The onnx converter (onnx/converter.py) walks eqns with per-primitive
handlers because it must LOWER each one; passes here only need to LOOK, so
the traversal is generic: `iter_eqns` yields every eqn at every nesting
depth together with a human-readable provenance path, and `sub_jaxprs`
finds the inner jaxprs of any call-like eqn (pjit/scan/while/cond/custom
vjp/remat) without a primitive table that would rot as jax evolves.
"""


def sub_jaxprs(eqn):
    """Yield (param_name, ClosedJaxpr-or-Jaxpr) for every inner jaxpr the
    eqn carries (pjit's `jaxpr`, cond's `branches` list, scan/while bodies,
    custom_*_call's `call_jaxpr`/`fun_jaxpr`...)."""
    import jax

    for k, v in eqn.params.items():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if isinstance(item, (jax.core.ClosedJaxpr, jax.core.Jaxpr)):
                yield k, item


def _raw(jaxpr_like):
    return jaxpr_like.jaxpr if hasattr(jaxpr_like, "jaxpr") else jaxpr_like


def iter_eqns(jaxpr, path="", max_depth=32):
    """Depth-first (eqn, provenance_path) over jaxpr and every sub-jaxpr.

    Provenance looks like ``eqns[12]/pjit:_bernoulli/eqns[4]`` — stable
    across runs of the same trace, good enough to locate the offender in a
    printed jaxpr. max_depth guards against pathological nesting.
    """
    if max_depth < 0:
        return
    for i, eqn in enumerate(jaxpr.eqns):
        here = f"{path}eqns[{i}]"
        yield eqn, here
        tag = eqn.params.get("name", "")
        label = f"{eqn.primitive.name}:{tag}" if tag else eqn.primitive.name
        for _, sub in sub_jaxprs(eqn):
            yield from iter_eqns(_raw(sub), f"{here}/{label}/",
                                 max_depth - 1)


def is_key_aval(aval):
    """True when aval is a typed PRNG key (jax.random.key) array."""
    import jax

    try:
        return jax.dtypes.issubdtype(aval.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def is_literal(atom):
    from jax._src.core import Literal

    return isinstance(atom, Literal)


def fmt_aval(aval):
    try:
        shape = "x".join(str(d) for d in aval.shape)
        return f"{aval.dtype}[{shape}]"
    except Exception:
        return str(aval)


def trace_layer(layer, *example_inputs, training=False):
    """Trace an nn.Layer's forward to a ClosedJaxpr, pure in its params.

    Uses Layer.functional_call (the same functional bridge jit/export
    use) with the autograd tape paused, so tracing never records grad
    nodes or static-Program ops. Nothing is compiled or executed on
    device beyond the trace itself.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..core.tape import global_tape
    from ..core.tensor import Tensor

    params, buffers = layer.functional_state()
    arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(np.asarray(a))
            for a in example_inputs]

    def pure(p, *xs):
        with global_tape().pause():
            out = layer.functional_call(p, [Tensor(x) for x in xs],
                                        buffers=buffers, training=training)
        return jax.tree_util.tree_map(
            lambda v: v._data if isinstance(v, Tensor) else v, out,
            is_leaf=lambda v: isinstance(v, Tensor))

    return jax.make_jaxpr(pure)(params, *arrs)
