"""Pallas kernel budget verifier: block/VMEM/accumulator mistakes fail
at lint time, not on a burned 900-second TPU bench round.

Every Pallas kernel family the repo ships (the TPP micro-kernel registry
``ops/tpp.py``, flash attention ``ops/flash_attention.py``, the NMS sweep
``ops/nms_pallas.py``) exposes an ``audit_manifest()``: a list of
declarative entries describing what each kernel compiles to at its
representative shapes — grid dims with their block edges, every
VMEM-resident buffer's block shape and dtype, scratch allocations, and
the matmul accumulator dtype. The manifest is pure arithmetic (no pallas
import, no tracing), so the whole audit runs in milliseconds.

Checks per entry (TPU facts per /opt/skills/guides/pallas_guide.md):

- ``kernel-grid-indivisible`` (error): a grid dim's block edge must
  divide the dim exactly — a ragged tail block reads out of bounds (or
  silently pads, depending on lowering: both are wrong answers);
- ``kernel-block-misaligned`` (warning/info): the minor-most block dim
  should be a multiple of the 128-lane register width (info: the block
  pads to a full lane tile, wasting lanes) and the second-minor a
  multiple of the dtype's sublane tile — 8 for f32, 16 for bf16, 32 for
  int8/fp8 (warning: every access pays a relayout);
- ``kernel-vmem-over-budget`` (error): streamed blocks are
  double-buffered by the Pallas pipeline (x2), scratch is resident (x1);
  the static total must fit the per-core VMEM budget (16 MiB) — the
  finding carries the per-buffer breakdown, largest first;
- ``kernel-low-precision-accumulator`` (error): a matmul-class kernel
  consuming bf16/int8/fp8 inputs must accumulate in float32 (the MXU
  accumulates f32; an int8/bf16 accumulator silently saturates/rounds).

CLI: ``python tools/contract_audit.py --pallas`` (and
``graph_lint.py --contracts``); tier-1: tests/test_sharding_gate.py.
"""
from .registry import Finding

RULES = {
    "kernel-grid-indivisible": "error",
    "kernel-block-misaligned": "warning",
    "kernel-vmem-over-budget": "error",
    "kernel-low-precision-accumulator": "error",
}

#: per-core VMEM (v4/v5 class cores; pallas_guide.md "~16 MB/core")
VMEM_BUDGET_BYTES = 16 * 1024 * 1024
LANE = 128
#: min sublane tile (second-minor dim) per dtype
SUBLANE = {"float32": 8, "int32": 8, "uint32": 8,
           "bfloat16": 16, "float16": 16,
           "int8": 32, "uint8": 32, "float8_e4m3fn": 32,
           "float8_e5m2": 32}
_ITEMSIZE = {"float32": 4, "int32": 4, "uint32": 4,
             "bfloat16": 2, "float16": 2,
             "int8": 1, "uint8": 1, "float8_e4m3fn": 1,
             "float8_e5m2": 1, "bool": 1}
LOW_PRECISION = ("bfloat16", "float16", "int8", "uint8",
                 "float8_e4m3fn", "float8_e5m2")


def _itemsize(dtype):
    return _ITEMSIZE.get(str(dtype), 4)


def buffer_bytes(buf):
    """Static VMEM bytes of one manifest buffer, double-buffering
    included (streamed blocks hold block N and block N+1 in flight)."""
    n = 1
    for d in buf.get("block", ()):
        n *= int(d)
    return n * _itemsize(buf.get("dtype", "float32")) * \
        (2 if buf.get("stream", True) else 1)


def vmem_breakdown(entry):
    """[(name, bytes)] largest first + the total — the per-buffer
    breakdown an over-budget finding names."""
    rows = [(b.get("name", f"buf{i}"), buffer_bytes(b))
            for i, b in enumerate(entry.get("buffers", ()))]
    rows.sort(key=lambda r: (-r[1], r[0]))
    return rows, sum(b for _, b in rows)


def audit_entry(entry, budget=VMEM_BUDGET_BYTES):
    """Findings for one manifest entry."""
    out = []
    kern = entry.get("kernel", "?")
    where = kern

    for dim, (size, block) in sorted(entry.get("grid", {}).items()):
        if block in (None, 0) or size in (None, 0):
            continue
        if int(size) % int(block):
            out.append(Finding(
                "kernel-grid-indivisible", "error",
                f"{kern}: grid dim '{dim}' = {size} is not divisible by "
                f"its block edge {block} — the last grid step reads a "
                f"ragged {size % block}-wide tail", where=where))

    lane_pads, sublane_bad = [], []
    for buf in entry.get("buffers", ()):
        block = tuple(int(d) for d in buf.get("block", ()))
        if len(block) < 2:
            continue
        name = buf.get("name", "?")
        dt = str(buf.get("dtype", "float32"))
        minor, second = block[-1], block[-2]
        if minor > 1 and minor % LANE:
            lane_pads.append(f"{name}[..{minor}]")
        sub = SUBLANE.get(dt, 8)
        if second > 1 and second % sub:
            sublane_bad.append(f"{name}[{second}x{minor} {dt}, "
                               f"min tile ({sub}, {LANE})]")
    if lane_pads:
        out.append(Finding(
            "kernel-block-misaligned", "info",
            f"{kern}: {len(lane_pads)} buffer(s) with a lane dim below "
            f"the {LANE}-lane register width ({', '.join(lane_pads[:5])})"
            " — each block pads to a full lane tile (wasted lanes)",
            where=where))
    if sublane_bad:
        out.append(Finding(
            "kernel-block-misaligned", "warning",
            f"{kern}: sublane dim not a multiple of the dtype min tile "
            f"({', '.join(sublane_bad[:5])}) — every access pays a "
            "relayout", where=where))

    rows, total = vmem_breakdown(entry)
    if total > budget:
        detail = ", ".join(f"{n}={b / 1024:.0f}KiB" for n, b in rows[:6])
        out.append(Finding(
            "kernel-vmem-over-budget", "error",
            f"{kern}: static VMEM {total / (1 << 20):.1f} MiB exceeds "
            f"the {budget / (1 << 20):.0f} MiB per-core budget "
            f"(streamed blocks double-buffered; breakdown: {detail}) — "
            "shrink the block edges or move a buffer to grid streaming",
            where=where))

    if entry.get("matmul"):
        in_dt = str(entry.get("in_dtype", "float32"))
        acc_dt = str(entry.get("acc_dtype", ""))
        if in_dt in LOW_PRECISION and acc_dt != "float32":
            out.append(Finding(
                "kernel-low-precision-accumulator", "error",
                f"{kern}: {in_dt} matmul accumulates in "
                f"{acc_dt or 'the input dtype'} — partial products "
                "saturate/round silently; accumulate in a float32 VMEM "
                "scratch (preferred_element_type=float32)", where=where))
    return out


def audit_tile(name, block, dtype="float32", budget=VMEM_BUDGET_BYTES,
               stream=True):
    """Findings for one synthetic streamed buffer tile — the planner's
    per-stage activation working set (analysis/plan_search.py) priced
    with the SAME rules as registered kernels: streamed blocks are
    double-buffered, the budget is the 16 MiB per-core VMEM. Alignment
    findings ride along at their usual severities; only the budget rule
    is an error."""
    entry = {"kernel": str(name), "matmul": False, "grid": {},
             "buffers": [{"name": "tile", "block": tuple(block),
                          "dtype": str(dtype), "stream": bool(stream)}]}
    return audit_entry(entry, budget=budget)


def collect_manifest():
    """Every registered kernel family's manifest entries. Imports the
    ops modules (jax import cost only — nothing compiles or runs)."""
    from ..ops import flash_attention, nms_pallas, tpp

    entries = []
    for mod in (tpp, flash_attention, nms_pallas):
        entries.extend(mod.audit_manifest())
    return entries


def audit_package(budget=VMEM_BUDGET_BYTES):
    """The full kernel audit over every registered family."""
    out = []
    for entry in collect_manifest():
        out.extend(audit_entry(entry, budget=budget))
    return out
