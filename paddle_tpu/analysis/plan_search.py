"""Auto-parallel plan search: enumerate, verify, score, emit.

ROADMAP item 4, the Alpa/MPMD line (arXiv:2412.14374) redone as *search
over verified static analyses* instead of live trial runs. For one
bundled tiny model the enumerator walks the dp/mp/pp/n_micro/compress
space, every candidate is **verified by the existing analyzers** —
rejection always names the failing pass, never crashes — survivors are
scored by :class:`cost_model.CostModel`, and the winner is emitted as a
ready-to-run ``SpmdTrainer`` / stage-graph config
(:func:`spmd.spmd_trainer_from_plan` /
:func:`stage.pipeline_trainer_from_plan` realize it).

The verification battery, per candidate:

1. **sharding-flow** — the plan's axis program (a shard_map psum over
   every plan axis, traced on an ``AbstractMesh`` with the PLAN's axis
   sizes; nothing allocates devices) runs through the full registered
   pass battery with the *deployment* mesh the host can actually build.
   A plan asking for more devices than exist is rejected by the real
   ``collective-axis-mismatch`` pass — same finding text a hand-built
   bad mesh gets. Valid plans additionally get their trainer-step
   *program class* traced (memoized per (model, dp, quantized) /
   (model, pp)) and the battery run on the real jaxpr; its
   :func:`sharding_flow.flow_summary` supplies measured collective
   bytes to the cost model.
2. **pallas VMEM** — the per-stage boundary-activation working set goes
   through :func:`pallas_audit.audit_tile` (the registered kernels'
   16 MiB double-buffered accounting); over-budget stages are rejected
   by ``kernel-vmem-over-budget``.
3. **handoff schema** — the stage-edge payload the plan would put on
   the wire is checked against the AST-extracted ``HANDOFF_SCHEMA`` /
   ``HANDOFF_SCHEMA_GRAD`` declarations via
   :func:`handoff_schema.validate`; a mismatch (e.g. asking to quantize
   the always-dense grad edge) is rejected as ``plan-handoff-mismatch``
   carrying the validator's edge/leaf/field message.
4. **HBM** — the cost model's per-device memory term against the
   budget (``plan-hbm-over-budget``).

CLI: ``python tools/plan_search.py --model gpt --top 5 --explain``;
``tools/graph_lint.py --plan`` folds the same reports into ``--all``.
Manifest-lazy like cost_model — a plain trainer never imports this.
"""
import numpy as np

from .registry import AnalysisReport, Finding, run_passes
from . import cost_model as _cm

__all__ = ["RULES", "SearchResult", "PLAN_MODELS", "enumerate_plans",
           "verify_plan", "search", "emit", "default_plan",
           "realize_trainer", "clear_cache"]

RULES = {
    "plan-space-empty": "error",
    "plan-handoff-mismatch": "error",
    "plan-ranked": "info",
    "plan-rejected": "info",
}

#: models the planner knows how to profile (the sharding targets' tiny
#: builders); pipeline plans additionally need model.pipeline_split
PLAN_MODELS = ("gpt", "bert", "ernie")

#: memoized trainer-step traces: key -> (AnalysisReport, flow_summary)
_TRACE_CACHE = {}
_PROFILE_CACHE = {}


def clear_cache():
    _TRACE_CACHE.clear()
    _PROFILE_CACHE.clear()


def _profile(model):
    if model not in _PROFILE_CACHE:
        if model not in PLAN_MODELS:
            raise ValueError(f"unknown model {model!r}; "
                             f"choose from {PLAN_MODELS}")
        _PROFILE_CACHE[model] = _cm.ModelProfile.trace(model)
    return _PROFILE_CACHE[model]


# ---------------------------------------------------------------------------
# enumeration
# ---------------------------------------------------------------------------


def enumerate_plans(profile, devices):
    """Every candidate the verifier will judge. Deliberately generous —
    infeasible combinations (mp without split layers, pp beyond the
    layer count, axes beyond the device pool) are enumerated anyway so
    their rejection is an ANALYZER finding, not a silent gap."""
    plans = []
    g = _cm.GLOBAL_BATCH
    dps = [d for d in (1, 2, 4, 8, 16) if d <= devices and g % d == 0]
    for dp in dps:
        plans.append(_cm.Plan(dp=dp))
        if dp > 1:
            plans.append(_cm.Plan(dp=dp, quantized_allreduce=True))
    for dp, mp in ((1, 2), (2, 2), (4, 2), (1, 4)):
        if dp * mp <= max(devices, 2) and g % dp == 0:
            plans.append(_cm.Plan(dp=dp, mp=mp))
    for pp in (2, 4):
        cuts = _equal_cuts(profile.n_layers, pp)
        for n_micro in (pp, 2 * pp, 4 * pp):
            if g % n_micro:
                continue
            for comp in (None, 8):
                plans.append(_cm.Plan(pp=pp, n_micro=n_micro,
                                      edge_compress=comp,
                                      stage_layers=cuts))
    return plans


def _equal_cuts(n_layers, pp):
    if pp <= 0 or n_layers % pp:
        return None
    per = n_layers // pp
    return [list(range(i * per, (i + 1) * per)) for i in range(pp)]


def default_plan(profile, devices):
    """The hand-written default every bundled test/doc uses: plain data
    parallel over the whole device pool, no compression."""
    g = _cm.GLOBAL_BATCH
    dp = max(d for d in (1, 2, 4, 8, 16)
             if d <= devices and g % d == 0)
    return _cm.Plan(dp=dp)


# ---------------------------------------------------------------------------
# verification (every rejection names the analyzer pass that fired)
# ---------------------------------------------------------------------------


class _DeployMesh:
    """Duck-typed deployment mesh (axis_names + shape dict is all the
    sharding-flow passes read): the best factorization the host's
    device pool can offer for the plan's axes — an axis the pool cannot
    fill gets what is left, and the collective pass reports the
    mismatch against the plan's traced sizes."""

    def __init__(self, names, wanted, devices):
        self.axis_names = tuple(names)
        shape = {}
        remaining = max(1, int(devices))
        for n, want in zip(names, wanted):
            got = want if want <= remaining else max(1, remaining)
            while remaining % got:
                got -= 1
            shape[n] = got
            remaining //= got
        self.shape = shape

    def __repr__(self):
        return f"_DeployMesh({self.shape})"


def _axis_program_report(plan, devices):
    """Trace the plan's axis program on an AbstractMesh with the PLAN's
    sizes and run the full pass battery against the deployment mesh."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import AbstractMesh, PartitionSpec as P

    names, sizes = plan.mesh_axes
    amesh = AbstractMesh(tuple(zip(names, sizes)))

    def axis_prog(x):
        for a in names:
            x = jax.lax.psum(x, a)
        return x

    f = shard_map(axis_prog, mesh=amesh, in_specs=P(), out_specs=P())
    closed = jax.make_jaxpr(f)(jnp.zeros((8, 8), jnp.float32))
    deploy = _DeployMesh(names, sizes, devices)
    return run_passes(closed, name=f"plan:{plan.describe()}",
                      mesh=deploy,
                      large_threshold=_sf().TARGET_THRESHOLD)


def _sf():
    from . import sharding_flow

    return sharding_flow


def _class_key(plan, model):
    if plan.pp > 1:
        return (model, "pp", plan.pp, plan.n_micro)
    if plan.quantized_allreduce:
        return (model, "dp_q", plan.dp)
    return (model, "dp_dense")


def _trace_class(plan, model, devices):
    """(AnalysisReport, flow_summary) of the plan's trainer-step
    program class, traced on the real (virtual-CPU) device pool and
    memoized. Dense-dp plans share one trace at max dp: the program is
    identical modulo batch, and its jaxpr carries no explicit
    collectives to measure anyway."""
    key = _class_key(plan, model)
    if key in _TRACE_CACHE:
        return _TRACE_CACHE[key]
    import jax

    sf = _sf()
    ndev = min(devices, len(jax.devices()))
    if plan.pp > 1:
        closed, kw = _trace_pipeline_class(model, plan, ndev)
    else:
        dp = plan.dp if plan.quantized_allreduce else \
            max(d for d in (1, 2, 4, 8) if d <= ndev)
        dp = min(dp, ndev)
        if plan.quantized_allreduce:
            from .. import flags as _flags

            old = {"quantized_allreduce":
                   _flags.get_flag("quantized_allreduce", False)}
            _flags.set_flags({"quantized_allreduce": True})
            try:
                trainer, batch, mesh = sf._tiny_train_setup(model, dp)
                closed, donated = sf._trace_trainer_step(trainer, batch)
            finally:
                _flags.set_flags(old)
        else:
            trainer, batch, mesh = sf._tiny_train_setup(model, dp)
            closed, donated = sf._trace_trainer_step(trainer, batch)
        kw = dict(mesh=mesh, donated=donated)
    rep = run_passes(closed, name=f"plan_class:{'/'.join(map(str, key))}",
                     large_threshold=sf.TARGET_THRESHOLD, **kw)
    flow = sf.flow_summary(closed, mesh=kw.get("mesh"),
                           large_threshold=sf.TARGET_THRESHOLD)
    _TRACE_CACHE[key] = (rep, flow)
    return _TRACE_CACHE[key]


def _trace_pipeline_class(model, plan, ndev):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..distributed.mesh import build_mesh
    from ..distributed.pipeline import PipelineTrainer
    from ..models import GPTConfig, GPTForCausalLM

    sf = _sf()
    if model != "gpt":
        raise ValueError(f"{model} has no pipeline_split")
    n_pp = min(plan.pp, ndev)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64,
                    num_layers=max(n_pp, 2), num_heads=4,
                    max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    pre, stages, post = m.pipeline_split(n_pp)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
    mesh = build_mesh((n_pp,), ("pp",), devices=jax.devices()[:n_pp])
    tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                         n_micro=plan.n_micro, schedule_mode="F-then-B")
    rng = np.random.RandomState(0)
    b, s = _cm.GLOBAL_BATCH, _cm.SEQ_LEN
    mb = b // tr.n_micro
    x = jnp.asarray(rng.randint(0, 256, (b, s)).astype(np.int32))
    y = jnp.asarray(rng.randint(0, 256, (b, s)).astype(np.int32))
    x_micro = x.reshape((tr.n_micro, mb, s))
    y_micro = y.reshape((tr.n_micro, mb, s))
    step = tr._build()
    lr = jnp.asarray(tr.optimizer.get_lr(), dtype=jnp.float32)
    closed = jax.make_jaxpr(step)(tr.params, tr.opt_state, tr.frozen,
                                  lr, x_micro, y_micro)
    return closed, dict(mesh=mesh, donated=sf._donated_of(closed))


def _edge_schema_findings(plan, profile):
    """Check the stage-edge payload this plan puts on the wire against
    the declared (AST-extracted) schemas — the real validator, wrapped
    so a mismatch is a named finding, not a crash."""
    if plan.pp <= 1:
        return []
    import jax

    from . import handoff_schema as hs

    mb = _cm.GLOBAL_BATCH // plan.n_micro
    dims = {"mb": mb, "t": profile.seq, "d": profile.hidden}
    out = []
    for edge, compress in (("mpmd_activation", plan.edge_compress),
                           ("mpmd_grad",
                            8 if plan.compress_grad_edge else None)):
        relpath, attr = hs.EDGES[edge]
        schema = hs.extract_declaration(relpath, attr)
        leaf = next(iter(schema["payload"]))
        shape = (mb, profile.seq, profile.hidden)
        if compress:
            payload = {leaf: (
                jax.ShapeDtypeStruct(shape, np.int8),
                jax.ShapeDtypeStruct(shape[:-1] + (1,), np.float32))}
            dt = None     # int8 wire values: skip the $act binding
        else:
            payload = {leaf: jax.ShapeDtypeStruct(shape, np.float32)}
            dt = {"act": "float32"}
        try:
            hs.validate(schema, payload, dims=dict(dims), dtypes=dt)
        except hs.HandoffMismatch as e:
            out.append(Finding(
                "plan-handoff-mismatch", "error",
                f"stage-edge payload rejected by the handoff-schema "
                f"validator: {e}", where=plan.describe()))
    return out


def _vmem_findings(plan, profile):
    """The per-stage boundary-activation working set through the Pallas
    VMEM accounting (pp plans; dp plans stream no stage tiles)."""
    if plan.pp <= 1:
        return []
    from . import pallas_audit

    mb = _cm.GLOBAL_BATCH // plan.n_micro
    block = (mb * profile.seq, profile.hidden)
    return [f for f in pallas_audit.audit_tile(
        f"plan.stage_act[{plan.describe()}]", block)
        if f.severity == "error"]


def verify_plan(plan, profile, devices=8, model=None, cm=None,
                trace_classes=True):
    """(error_findings, flow_summary|None) — empty findings = valid.

    Composes the existing analyzers; every rejection is a Finding whose
    ``pass_name`` names the analyzer that fired. Never raises on a bad
    plan."""
    cm = cm or _cm.CostModel()
    model = model or profile.name
    errs = list(cm.check_config(plan, profile, devices))
    if errs:
        return errs, None
    rep = _axis_program_report(plan, devices)
    errs.extend(rep.errors)
    errs.extend(_vmem_findings(plan, profile))
    errs.extend(_edge_schema_findings(plan, profile))
    errs.extend(cm.check_memory(plan, profile))
    if errs:
        return errs, None
    flow = None
    if trace_classes:
        class_rep, flow = _trace_class(plan, model, devices)
        errs.extend(class_rep.errors)
    return errs, flow


# ---------------------------------------------------------------------------
# search + report
# ---------------------------------------------------------------------------


class SearchResult:
    """ranked: [(Plan, score dict)] best-first; rejected:
    [(Plan, [Finding])]; profile: the traced ModelProfile."""

    def __init__(self, model, profile, ranked, rejected):
        self.model = model
        self.profile = profile
        self.ranked = ranked
        self.rejected = rejected

    @property
    def best(self):
        return self.ranked[0] if self.ranked else None

    def to_report(self, top=None):
        rep = AnalysisReport(name=f"plan_{self.model}")
        if not self.ranked:
            rep.add(Finding(
                "plan-space-empty", "error",
                f"{self.model}: every one of "
                f"{len(self.rejected)} candidate plan(s) was rejected "
                "— no valid partitioning under the given budgets",
                where=self.model))
        for i, (plan, score) in enumerate(
                self.ranked[:top] if top else self.ranked):
            rep.add(Finding(
                "plan-ranked", "info",
                f"#{i + 1} {plan.describe()}: total "
                f"{score['total_s'] * 1e6:.1f}us (compute "
                f"{score['compute_s'] * 1e6:.1f}us, comm "
                f"{score['comm_s'] * 1e6:.1f}us, "
                f"{score['mem_bytes_per_device'] / (1 << 20):.2f} "
                "MiB/device)", where=plan.describe()))
        for plan, errs in self.rejected:
            first = errs[0]
            rep.add(Finding(
                "plan-rejected", "info",
                f"{plan.describe()}: rejected by "
                f"{sorted({e.pass_name for e in errs})} — "
                f"{first.message}", where=plan.describe()))
        return rep.sort()

    def to_dict(self, top=None):
        return {
            "model": self.model,
            "profile": self.profile.to_dict(),
            "ranked": [dict(score, describe=plan.describe())
                       for plan, score in
                       (self.ranked[:top] if top else self.ranked)],
            "rejected": [{"plan": plan.to_dict(),
                          "passes": sorted({e.pass_name for e in errs}),
                          "messages": [e.message for e in errs]}
                         for plan, errs in self.rejected],
        }


def search(model, devices=None, hbm_bytes=None, cm=None):
    """Enumerate, verify, score and rank plans for one bundled model."""
    import jax

    ndev = devices or len(jax.devices())
    profile = _profile(model)
    cm = cm or _cm.CostModel(
        hbm_bytes=hbm_bytes or _cm.DEFAULT_HBM_BYTES)
    ranked, rejected = [], []
    for plan in enumerate_plans(profile, ndev):
        errs, flow = verify_plan(plan, profile, devices=ndev,
                                 model=model, cm=cm)
        if errs:
            rejected.append((plan, errs))
            continue
        ranked.append((plan, cm.score(plan, profile, flow=flow)))
    ranked.sort(key=lambda ps: ps[1]["total_s"])
    return SearchResult(model, profile, ranked, rejected)


# ---------------------------------------------------------------------------
# emission: plan -> ready-to-run config
# ---------------------------------------------------------------------------


def emit(plan, profile):
    """The winning plan as a ready-to-run, JSON-able trainer config.

    ``kind="spmd"`` realizes as a :class:`SpmdTrainer`
    (distributed/spmd.py ``spmd_trainer_from_plan``); ``kind="stage_graph"``
    as a FLAGS_mpmd :class:`PipelineTrainer` whose runner builds the
    typed-edge StageGraph (distributed/stage.py
    ``pipeline_trainer_from_plan``). ``flags`` must be set BEFORE
    construction — both builders check (construction consumes flags)."""
    names, sizes = plan.mesh_axes
    cfg = {
        "model": profile.name,
        "mesh": {"shape": list(sizes), "axes": list(names)},
        "global_batch": _cm.GLOBAL_BATCH,
        "seq_len": profile.seq,
        "plan": plan.to_dict(),
    }
    if plan.pp > 1:
        cfg["kind"] = "stage_graph"
        cfg["flags"] = {"mpmd": True}
        cfg["pipeline"] = {
            "n_micro": plan.n_micro,
            "schedule": "1F1B",
            "stage_layers": plan.stage_layers
            or _equal_cuts(profile.n_layers, plan.pp),
            "compress": plan.edge_compress,
        }
    else:
        cfg["kind"] = "spmd"
        cfg["flags"] = {
            "quantized_allreduce": plan.quantized_allreduce}
        cfg["spmd"] = {"dp_axis": "dp",
                       "tensor_parallel": plan.mp > 1}
    return cfg


def realize_trainer(config):
    """Build the bundled tiny model + optimizer the config's profile
    describes and hand them to the distributed-layer builders. SETS
    ``config["flags"]`` process-wide first (trainer construction
    consumes flags); restore via ``paddle_tpu.flags.set_flags`` when
    done. Returns ``(trainer, batch arrays)`` — the batch is the
    model's pretrain tuple at the plan's global batch size."""
    import paddle_tpu as paddle
    from .. import flags as _flags

    _flags.set_flags(dict(config.get("flags") or {}))
    model_name = config["model"]
    g, s = int(config["global_batch"]), int(config["seq_len"])
    rng = np.random.RandomState(0)
    paddle.seed(0)
    if config["kind"] == "stage_graph":
        from ..distributed.stage import pipeline_trainer_from_plan
        from ..models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=64, dropout=0.0)
        m = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=m.parameters())
        trainer = pipeline_trainer_from_plan(config, m, opt)
    else:
        from ..distributed.spmd import spmd_trainer_from_plan
        from .sharding_flow import _tiny_train_setup

        base, _, _ = _tiny_train_setup(model_name, dp=1)
        trainer = spmd_trainer_from_plan(
            config, base.layer, base.optimizer, loss_fn=base.loss_fn)
    ids = rng.randint(0, 256, (g, s)).astype(np.int32)
    labels = rng.randint(0, 256, (g, s)).astype(np.int32)
    batch = (ids, np.zeros((g, s), np.int32), labels) \
        if model_name == "bert" else (ids, labels)
    return trainer, batch
