"""AST-based source linter with framework-specific rules, run over
paddle_tpu/ itself (tools/graph_lint.py --all and the tier-1 gate).

Rules target the hazards the jaxpr passes cannot see because they happen
BEFORE tracing:

  np-random-in-traced-code : np.random.* inside a function of the
      trace-reachable core (nn/, models/, ops/, tensor/, core/, amp/).
      Under jit the draw happens once at trace time and the sample is
      BAKED into the compiled program — every step replays it. Layer
      __init__ / parameter-init code is exempt (runs eagerly, once).
  time-in-traced-code : time.time()/perf_counter() in the same scope —
      a trace-time constant masquerading as a clock.
  mutable-default-arg : list/dict/set literal defaults on methods of
      nn.Layer subclasses — shared across every instance of the layer
      (the classic aliasing bug, promoted to error because layers are
      long-lived and cloned).
  private-model-import-in-serving : a module under inference/ or
      serving/ importing a module-PRIVATE name (``_foo``) from
      ``models.*``. The serving tier is model-agnostic by contract
      (docs/SERVING.md): models plug in through the DecodeModel registry
      (serving/decode_model.py), never by reaching into a model module's
      privates — that coupling is exactly what ISSUE 6 removed.
  step-loop-host-sync : a per-step host pull (np.asarray /
      jax.device_get / .item() / .block_until_ready()) inside the
      trainer/serving HOT-PATH functions (SpmdTrainer.train_step's
      implementation chain, ServingEngine.step's) — each one serializes
      the dispatch pipeline once per step. The deliberate syncs (the
      benchmark sync, the decode token fetch, the windowed deferred
      guard drain, host-side batch ingest) carry
      ``# lint: allow(step-loop-host-sync)``; anything new is an error
      (the ISSUE 11 satellite: hot paths stay clean).
  nonreduced-client-output : a function in federated/ returns a
      ``client_map`` result that never passed through a ``federated_*``
      reduce (or ``collective.client_reduce``). Client-placed values
      escaping a federated API leak per-client data to the server
      unaggregated AND skip the metered collective chokepoint — the
      MapReduce contract (docs/FEDERATED.md) is map THEN reduce. A
      deliberate client-placed return (e.g. ``client_map`` itself)
      carries ``# lint: allow(client_output)``.
  unlocked-thread-shared-write : in a module that spawns daemon threads
      (THREAD_SHARED_MODULES: the blackbox sentinel, the monitor
      registry, the profiler), a write to module-global shared state
      reachable from a thread body that is not under the module's
      designated lock. The GIL makes ``x += 1`` interleavable, not
      atomic — cross-thread mutations take the lock or carry
      ``# lint: allow(thread-shared-write)`` with a reason (e.g. a
      single-slot boolean latch).

Suppression: a trailing ``# lint: allow(<rule>)`` comment on the
offending line acknowledges a documented, deliberate exception (e.g. an
eager host op that already warns under tracing). The marker grammar and
alias table are shared with the contract-auditor passes
(analysis/allowlist.py).
"""
import ast
import os

from .allowlist import RULE_ALIASES as _RULE_ALIASES  # noqa: F401 (compat)
from .allowlist import allowed as _shared_allowed
from .registry import Finding

# packages whose function bodies are reachable from a jit trace
_TRACED_PKGS = ("nn", "models", "ops", "tensor", "core", "amp")
# packages forming the serving tier: model access ONLY via the DecodeModel
# registry, never a model module's privates
_SERVING_PKGS = ("inference", "serving")
# methods that run eagerly at construction time, never inside a trace
_INIT_METHODS = {"__init__", "__init_subclass__", "reset_parameters",
                 "_init_weights", "extra_repr", "__repr__"}

RULES = {
    "np-random-in-traced-code": "error",
    "time-in-traced-code": "warning",
    "mutable-default-arg": "error",
    "private-model-import-in-serving": "error",
    "nonreduced-client-output": "error",
    "step-loop-host-sync": "error",
    "unlocked-thread-shared-write": "error",
    "syntax-error": "error",
}

#: per-step hot-path functions policed by step-loop-host-sync: the
#: train-step and serving-step implementation chains. Keyed by the
#: module's path relative to the paddle_tpu package root.
HOT_PATHS = {
    os.path.join("distributed", "spmd.py"): {
        "train_step", "_train_step_impl", "_finish_step",
        "_drain_verdicts"},
    os.path.join("inference", "serving.py"): {
        "step", "_step_inner", "_step_inner_sync", "_step_inner_async",
        "_step_speculative", "_advance_prefill", "_activate",
        "_admit_one_inner", "_advance_and_admit", "_dispatch_decode",
        "_apply_decode"},
}

#: dotted call names that pull device values to the host
_SYNC_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get"}
#: method names that pull device values to the host when called
_SYNC_METHODS = {"item", "block_until_ready"}

#: modules that spawn daemon threads (or are mutated cross-thread) and
#: their designated lock name — the unlocked-thread-shared-write rule
#: polices writes to module-global state reachable from thread bodies.
#: Keyed by path relative to the paddle_tpu package root.
THREAD_SHARED_MODULES = {
    os.path.join("monitor", "blackbox.py"): "_LOCK",
    os.path.join("monitor", "registry.py"): "_lock",
    os.path.join("profiler", "__init__.py"): "_LOCK",
}

# the shared marker grammar lives in analysis/allowlist.py
_allowed = _shared_allowed


def _dotted(node):
    """'np.random.uniform' for an Attribute/Call chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_layer_class(cls):
    for b in cls.bases:
        name = _dotted(b) or (b.id if isinstance(b, ast.Name) else "")
        if name.split(".")[-1] in ("Layer", "Module"):
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel_path, lines, traced, serving=False,
                 federated=False, hot_funcs=None):
        self.rel = rel_path
        self.lines = lines
        self.traced = traced
        self.serving = serving
        self.federated = federated
        self.hot_funcs = hot_funcs or frozenset()
        self.findings = []
        self._func_stack = []
        self._class_stack = []
        # per-function {name: lineno} of client_map results not yet passed
        # through a federated_* reduce (nonreduced-client-output)
        self._client_vals = []

    def _emit(self, rule, lineno, message):
        if _allowed(self.lines, lineno, rule):
            return
        self.findings.append(Finding(
            rule, RULES[rule], message, where=f"{self.rel}:{lineno}"))

    # -- function / class scoping ------------------------------------------
    def visit_ClassDef(self, node):
        self._class_stack.append(node)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node):
        if (self._class_stack and _is_layer_class(self._class_stack[-1])
                and self._func_stack == []):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    self._emit(
                        "mutable-default-arg", d.lineno,
                        f"mutable default argument on "
                        f"{self._class_stack[-1].name}.{node.name} — "
                        "shared across every call and instance; default "
                        "to None and build inside the body")
        self._func_stack.append(node)
        self._client_vals.append({})
        self.generic_visit(node)
        self._client_vals.pop()
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- nonreduced-client-output bookkeeping (federated/ modules) ----------
    @staticmethod
    def _is_client_map_call(node):
        return (isinstance(node, ast.Call)
                and _dotted(node.func).split(".")[-1] == "client_map")

    @staticmethod
    def _is_reduce_call(node):
        last = _dotted(node.func).split(".")[-1]
        return last.startswith("federated_") or last == "client_reduce"

    def visit_Assign(self, node):
        if self.federated and self._client_vals:
            scope = self._client_vals[-1]
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if self._is_client_map_call(node.value):
                for n in names:
                    scope[n] = node.lineno
            else:
                for n in names:
                    scope.pop(n, None)   # rebound to something else
        self.generic_visit(node)

    def _mark_reduced(self, node):
        """A federated_* reduce consumed these args: clear every Name
        reachable inside them (generous by design — a lint heuristic)."""
        scope = self._client_vals[-1]
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name):
                    scope.pop(sub.id, None)

    def visit_Return(self, node):
        if self.federated and self._client_vals and node.value is not None:
            scope = self._client_vals[-1]
            parts = (node.value.elts
                     if isinstance(node.value, (ast.Tuple, ast.List))
                     else [node.value])
            fname = self._func_stack[-1].name if self._func_stack else "?"
            for part in parts:
                escaped = (isinstance(part, ast.Name) and part.id in scope) \
                    or self._is_client_map_call(part)
                if escaped:
                    self._emit(
                        "nonreduced-client-output", node.lineno,
                        f"{fname} returns a client_map result that never "
                        "passed through a federated_* reduce: client-"
                        "placed values must aggregate via federated_sum/"
                        "mean/weighted_mean (the metered collective "
                        "chokepoint) before escaping a federated API, or "
                        "carry `# lint: allow(client_output)` when client "
                        "placement is the contract")
        self.generic_visit(node)

    def _in_traced_scope(self):
        if not self.traced or not self._func_stack:
            return False
        return self._func_stack[0].name not in _INIT_METHODS

    def _in_hot_scope(self):
        """Inside a policed per-step hot-path function (closures nested
        in one count — they run per step too)."""
        return any(f.name in self.hot_funcs for f in self._func_stack)

    # -- import rules -------------------------------------------------------
    def visit_ImportFrom(self, node):
        # serving tier: `from ..models.X import _private` (any nesting,
        # module- or function-level) couples the engine to one model's
        # internals — the DecodeModel registry is the doorway
        mod = node.module or ""
        if self.serving and (mod == "models" or mod.startswith("models.")
                             or ".models." in mod
                             or mod.endswith(".models")):
            private = sorted(a.name for a in node.names
                             if a.name.startswith("_"))
            if private:
                self._emit(
                    "private-model-import-in-serving", node.lineno,
                    f"serving code imports module-private "
                    f"{', '.join(private)} from {mod!r}: the serving "
                    "tier is model-agnostic — go through the DecodeModel "
                    "registry (paddle_tpu/serving/decode_model.py) or "
                    "register an adapter on the model module")
        self.generic_visit(node)

    # -- call-site rules ----------------------------------------------------
    def visit_Call(self, node):
        name = _dotted(node.func)
        if self.federated and self._client_vals \
                and self._is_reduce_call(node):
            self._mark_reduced(node)
        if self.hot_funcs and self._in_hot_scope():
            last = name.split(".")[-1]
            if name in _SYNC_CALLS or (last in _SYNC_METHODS
                                       and "." in name):
                self._emit(
                    "step-loop-host-sync", node.lineno,
                    f"{name}(...) inside per-step hot path "
                    f"{self._func_stack[-1].name}: a host pull here "
                    "serializes the dispatch pipeline EVERY step — "
                    "defer/batch the fetch (docs/PERF.md), or mark a "
                    "deliberate sync with "
                    "`# lint: allow(step-loop-host-sync)`")
        if self._in_traced_scope():
            if name.startswith(("np.random.", "numpy.random.")) or \
                    name in ("np.random", "numpy.random"):
                self._emit(
                    "np-random-in-traced-code", node.lineno,
                    f"{name}(...) in jit-reachable code: under a trace "
                    "the draw happens once and the sample is baked into "
                    "the compiled program — use jax.random with a "
                    "threaded key (or mark a documented eager host op "
                    "with `# lint: allow(np-random-in-traced-code)`)")
            elif name in ("time.time", "time.perf_counter",
                          "time.monotonic"):
                self._emit(
                    "time-in-traced-code", node.lineno,
                    f"{name}() in jit-reachable code: a trace-time "
                    "constant, frozen into the compiled program")
        self.generic_visit(node)


def _dotted_last(node):
    d = _dotted(node)
    return d.split(".")[-1] if d else ""


class _ThreadScan(ast.NodeVisitor):
    """Phase 1 of the thread-discipline lint: module globals, function
    defs (by simple name), intra-module call edges, thread-body roots."""

    def __init__(self):
        self.module_globals = set()
        self.funcs = {}          # name -> [FunctionDef]
        self.calls = {}          # func name -> {called simple names}
        self.thread_roots = set()
        self.lock_seen = False
        self._stack = []
        self._class_bases = []

    def set_lock(self, lock_name):
        self._lock_name = lock_name

    def visit_ClassDef(self, node):
        bases = [_dotted_last(b) if not isinstance(b, ast.Name) else b.id
                 for b in node.bases]
        self._class_bases.append(bases)
        self.generic_visit(node)
        self._class_bases.pop()

    def _visit_func(self, node):
        self.funcs.setdefault(node.name, []).append(node)
        # a Thread subclass's run() IS a thread body
        if node.name == "run" and self._class_bases \
                and any(b.endswith("Thread") for b in self._class_bases[-1]):
            self.thread_roots.add("run")
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _visit_assign_targets(self, targets):
        if self._stack:
            return
        for t in targets:
            if isinstance(t, ast.Name):
                self.module_globals.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                self._visit_assign_targets(list(t.elts))

    def visit_Assign(self, node):
        self._visit_assign_targets(node.targets)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._visit_assign_targets([node.target])
        self.generic_visit(node)

    def visit_Call(self, node):
        name = _dotted(node.func)
        if self._stack:
            self.calls.setdefault(self._stack[-1], set()).add(
                name.split(".")[-1])
        if name.split(".")[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _dotted_last(kw.value) if not isinstance(
                        kw.value, ast.Name) else kw.value.id
                    if tgt:
                        self.thread_roots.add(tgt)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr == getattr(self, "_lock_name", None):
            self.lock_seen = True
        self.generic_visit(node)

    def visit_Name(self, node):
        if node.id == getattr(self, "_lock_name", None):
            self.lock_seen = True
        self.generic_visit(node)


class _WriteScan(ast.NodeVisitor):
    """Phase 2: inside one (thread-reachable) function, flag writes to
    module-global-rooted state outside `with <lock>:` blocks."""

    def __init__(self, module_globals, lock_name, rel, lines, emit):
        self.module_globals = module_globals
        self.lock_name = lock_name
        self.rel = rel
        self.lines = lines
        self.emit = emit
        self._lock_depth = 0
        self._locals = set()
        self._globals_decl = set()

    def prime(self, func):
        args = func.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            self._locals.add(a.arg)
        def bound_names(t, out):
            # only PLAIN name bindings shadow: `x = ...`, `x, y = ...`.
            # A Subscript/Attribute target (`_STATE["k"] = v`) mutates
            # the module object — its root must NOT count as local
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List, ast.Starred)):
                for el in getattr(t, "elts", [t.value] if isinstance(
                        t, ast.Starred) else []):
                    bound_names(el, out)

        for node in ast.walk(func):
            if isinstance(node, ast.Global):
                self._globals_decl.update(node.names)
            elif isinstance(node, ast.arg):
                # nested-def / lambda parameters shadow too
                self._locals.add(node.arg)
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    bound_names(t, self._locals)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                bound_names(node.target, self._locals)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                bound_names(node.optional_vars, self._locals)
        self._locals -= self._globals_decl
        return self

    # nested defs are visited for writes too (they run on the thread),
    # but their params/locals shadow — good enough for a lint heuristic

    def _is_locked_with(self, node):
        for item in node.items:
            if _dotted_last(item.context_expr) == self.lock_name:
                return True
        return False

    def visit_With(self, node):
        locked = self._is_locked_with(node)
        if locked:
            self._lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._lock_depth -= 1

    visit_AsyncWith = visit_With

    def _root_name(self, t):
        while isinstance(t, (ast.Attribute, ast.Subscript)):
            t = t.value
        return t.id if isinstance(t, ast.Name) else None

    def _check_target(self, t, lineno):
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._check_target(el, lineno)
            return
        shared = False
        if isinstance(t, ast.Name):
            shared = t.id in self._globals_decl \
                or (t.id in self.module_globals
                    and t.id not in self._locals)
        else:
            root = self._root_name(t)
            shared = root is not None and root in self.module_globals \
                and root not in self._locals
        if shared and self._lock_depth == 0:
            name = self._root_name(t) if not isinstance(t, ast.Name) \
                else t.id
            self.emit(
                "unlocked-thread-shared-write", lineno,
                f"write to module-shared {name!r} reachable from a "
                f"daemon-thread body without holding {self.lock_name} — "
                "the GIL interleaves, it does not serialize; take the "
                "lock or mark a deliberate single-slot latch with "
                "`# lint: allow(thread-shared-write)`")

    def visit_Assign(self, node):
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)


def lint_thread_discipline(source, rel_path="<string>", lock_name="_LOCK"):
    """The unlocked-thread-shared-write rule over one module: find
    thread bodies (``threading.Thread(target=...)`` targets and
    ``Thread``-subclass ``run`` methods), walk the same-module call
    graph they can reach, and flag writes to module-global-rooted state
    outside ``with <lock_name>:``. Returns [Finding]."""
    findings = []
    lines = source.splitlines()

    def emit(rule, lineno, message):
        if not _allowed(lines, lineno, rule):
            findings.append(Finding(rule, RULES[rule], message,
                                    where=f"{rel_path}:{lineno}"))

    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("syntax-error", "error",
                        f"unparseable source: {e}", where=rel_path)]
    scan = _ThreadScan()
    scan.set_lock(lock_name)
    scan.visit(tree)
    if not scan.lock_seen:
        findings.append(Finding(
            "unlocked-thread-shared-write",
            RULES["unlocked-thread-shared-write"],
            f"{rel_path} is declared thread-shared "
            f"(THREAD_SHARED_MODULES) but its designated lock "
            f"{lock_name!r} appears nowhere in the module",
            where=rel_path))
    if not scan.thread_roots:
        return findings
    # names reachable from the thread bodies over same-module calls
    reach, frontier = set(scan.thread_roots), list(scan.thread_roots)
    while frontier:
        fn = frontier.pop()
        for callee in scan.calls.get(fn, ()):
            if callee in scan.funcs and callee not in reach:
                reach.add(callee)
                frontier.append(callee)
    for fname in sorted(reach):
        for func in scan.funcs.get(fname, ()):
            _WriteScan(scan.module_globals, lock_name, rel_path, lines,
                       emit).prime(func).visit(func)
    findings.sort(key=lambda f: f.where)
    return findings


def lint_source(source, rel_path="<string>", traced=True, serving=None,
                federated=None, hot_funcs=None, thread_lock=None):
    """Lint one python source string; returns a list of Finding.
    serving=None / federated=None derive the tier flags from rel_path
    (modules under inference|serving/ resp. federated/); hot_funcs=None
    derives the step-loop-host-sync function set from HOT_PATHS;
    thread_lock=None derives the thread-discipline lock from
    THREAD_SHARED_MODULES."""
    if serving is None:
        serving = _is_serving_module(rel_path)
    if federated is None:
        federated = _is_federated_module(rel_path)
    if hot_funcs is None:
        hot_funcs = HOT_PATHS.get(rel_path, frozenset())
    if thread_lock is None:
        thread_lock = THREAD_SHARED_MODULES.get(rel_path)
    tree = ast.parse(source)
    v = _Visitor(rel_path, source.splitlines(), traced, serving=serving,
                 federated=federated, hot_funcs=hot_funcs)
    v.visit(tree)
    if thread_lock:
        v.findings.extend(lint_thread_discipline(source, rel_path,
                                                 thread_lock))
    v.findings.sort(key=lambda f: f.where)
    return v.findings


def _is_traced_module(rel_path):
    top = rel_path.split(os.sep)[0]
    if top not in _TRACED_PKGS:
        return False
    # vision/io/text/datasets are host-side by design; nn/, models/ etc.
    # are fully trace-reachable
    return True


def _is_serving_module(rel_path):
    return rel_path.split(os.sep)[0] in _SERVING_PKGS


def _is_federated_module(rel_path):
    return rel_path.split(os.sep)[0] == "federated"


def lint_path(root=None):
    """Lint the paddle_tpu package tree; returns a list of Finding."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = []
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            try:
                findings.extend(
                    lint_source(src, rel, traced=_is_traced_module(rel)))
            except SyntaxError as e:   # pragma: no cover — repo is valid
                findings.append(Finding(
                    "syntax-error", "error",
                    f"unparseable source: {e}", where=rel))
    return findings
