"""Standard analysis targets: the bundled models' forwards and the serving
engine's decode step, traced to jaxprs and run through the pass battery.

Shapes are CPU-shrunk (the tests/test_perf_budgets.py convention) so the
whole battery — trace + passes, no compilation — fits inside the tier-1
budget. Python warnings raised DURING tracing (truncated dtypes, baked
trace-time draws…) are converted into findings under the synthetic pass
name ``trace-warnings`` so dtype-hygiene regressions in model code fail
the same gate as jaxpr-level findings.
"""
import warnings

from .registry import Finding, run_passes

# small-but-structural configs: 2 layers keeps every eqn pattern of the
# full models (block stacking, final norm, tied head) at trace cost ~100ms
_MODEL_DIMS = dict(vocab_size=256, hidden_size=64, num_layers=2,
                   num_heads=4, dropout=0.0)

MODEL_TARGETS = ("gpt", "bert", "ernie")


def _build_model(name):
    import paddle_tpu as paddle
    from ..models import (BertConfig, BertModel, ErnieConfig, ErnieModel,
                          GPTConfig, GPTForCausalLM)

    paddle.seed(0)
    if name == "gpt":
        m = GPTForCausalLM(GPTConfig(max_seq_len=64, **_MODEL_DIMS))
    elif name == "bert":
        m = BertModel(BertConfig(max_position=64, intermediate_size=256,
                                 **_MODEL_DIMS))
    elif name == "ernie":
        m = ErnieModel(ErnieConfig(max_position=64, intermediate_size=256,
                                   **_MODEL_DIMS))
    else:
        raise ValueError(
            f"unknown model target {name!r}; choose from {MODEL_TARGETS}")
    m.eval()
    return m


def _trace_with_warnings(trace_fn):
    """Run trace_fn, returning (closed_jaxpr, [Finding from warnings])."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        closed = trace_fn()
    findings = [
        Finding("trace-warnings", "warning",
                f"python warning during trace: {w.category.__name__}: "
                f"{w.message}", where=f"{w.filename}:{w.lineno}")
        for w in caught]
    return closed, findings


def analyze_model(name, training=False, **run_kwargs):
    """Trace one bundled model's forward and run the full pass battery."""
    import jax.numpy as jnp

    from .jaxpr_utils import trace_layer

    m = _build_model(name)
    ids = jnp.zeros((2, 16), jnp.int32)
    closed, warn_findings = _trace_with_warnings(
        lambda: trace_layer(m, ids, training=training))
    report = run_passes(closed, name=f"{name}_forward", **run_kwargs)
    report.extend(warn_findings)
    return report.sort()


def analyze_serving_decode(**run_kwargs):
    """The ServingEngine greedy decode step — the serve hot loop.

    The engine donates its KV caches (donate_argnums=(1, 2) on
    _step_greedy); that intent is threaded into the donation-miss pass.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.serving import ServingEngine

    def build():
        eng = ServingEngine(_build_model("gpt"), max_batch=2)
        pos = jnp.zeros((eng.B,), jnp.int32)
        tok = jnp.zeros((eng.B,), jnp.int32)
        return jax.make_jaxpr(eng._step_greedy)(
            eng._params, eng._kc, eng._vc, tok, pos)

    closed, warn_findings = _trace_with_warnings(build)
    report = run_passes(closed, name="serve_decode_step",
                        donated=_cache_invars(closed), **run_kwargs)
    report.extend(warn_findings)
    return report.sort()


def _cache_invars(closed):
    """Indices of invars that look like the donated KV caches: rank >= 4
    arrays (layers x batch x seq x heads…) — the only buffers
    _step_greedy donates."""
    out = set()
    for i, iv in enumerate(closed.jaxpr.invars):
        shp = getattr(iv.aval, "shape", ())
        if shp is not None and len(shp) >= 4:
            out.add(i)
    return out
