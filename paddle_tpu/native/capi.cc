// C inference API — native shim over the paddle_tpu predictor.
//
// Reference parity: paddle/fluid/inference/capi/ (PD_NewAnalysisConfig,
// PD_NewPredictor, PD_PredictorRun, paddle_c_api.h) — the C surface that the Go
// (go/paddle/predictor.go) and R clients wrap.
//
// TPU-native design: the predictor's execution engine is XLA reached through
// Python (jit.load -> jax), so the C ABI embeds the CPython interpreter rather
// than re-implementing a runtime: each call acquires the GIL (PyGILState) and
// drives paddle_tpu.inference. Inside an existing Python process (the test
// path, and any embedder that already runs Python) the resident interpreter is
// reused; standalone C hosts get one via Py_Initialize.
//
// API (see native/paddle_tpu_capi.h):
//   PD_Init() / PD_Finalize()
//   PD_CreatePredictor(model_prefix)        -> handle (0 on failure)
//   PD_PredictorRunFloat(h, in, shape, ndim, out_buf, out_shape, max_*)
//   PD_DestroyPredictor(h)
//   PD_GetLastError()                       -> thread-local message
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

thread_local std::string g_last_error;

void set_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  g_last_error = where;
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      g_last_error += ": ";
      g_last_error += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Predictor {
  PyObject* obj;  // paddle_tpu TranslatedLayer / Predictor callable
};

struct Trainer {
  PyObject* obj;     // paddle_tpu SpmdTrainer (params held device-side)
  double last_loss;
};

// Call a function in the given bridge module; returns new reference or null
// (error recorded). Steals nothing.
PyObject* call_bridge(const char* module, const char* fn, PyObject* args) {
  PyObject* mod = PyImport_ImportModule(module);
  if (!mod) {
    set_error("import bridge failed");
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (!f) {
    set_error("bridge function missing");
    return nullptr;
  }
  PyObject* res = PyObject_CallObject(f, args);
  Py_DECREF(f);
  if (!res) set_error(fn);
  return res;
}

// Validate a shape and return the element count, or -1.
int64_t checked_numel(const int64_t* shape, int ndim) {
  if (ndim <= 0 || ndim > 16) return -1;
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) {
    if (shape[i] <= 0 || n > (int64_t{1} << 40) / (shape[i] + 1)) return -1;
    n *= shape[i];
  }
  return n;
}

PyObject* shape_list(const int64_t* shape, int ndim) {
  PyObject* shp = PyList_New(ndim);
  if (!shp) return nullptr;
  for (int i = 0; i < ndim; ++i) {
    PyObject* v = PyLong_FromLongLong(shape[i]);
    if (!v) {
      Py_DECREF(shp);
      return nullptr;
    }
    PyList_SET_ITEM(shp, i, v);
  }
  return shp;
}

}  // namespace

extern "C" {

const char* PD_GetLastError() { return g_last_error.c_str(); }

int PD_Init() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) return -1;
    // Release the GIL held by the initializing thread: callers (the Go
    // client migrates goroutines across OS threads) reach the interpreter
    // via PyGILState_Ensure, which deadlocks if the init thread keeps the
    // GIL forever. Saving the thread state here makes every later call —
    // from ANY OS thread, including this one — go through PyGILState.
    PyEval_SaveThread();
  }
  return 0;
}

void PD_Finalize() {
  // no-op when embedded in a live Python process; standalone hosts may call
  // Py_FinalizeEx themselves once all predictors are destroyed
}

void* PD_CreatePredictor(const char* model_prefix) {
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.jit");
  if (!mod) {
    set_error("import paddle_tpu.jit failed");
  } else {
    PyObject* loaded =
        PyObject_CallMethod(mod, "load", "s", model_prefix);
    if (!loaded) {
      set_error("jit.load failed");
    } else {
      Predictor* p = new Predictor{loaded};
      result = p;
    }
    Py_DECREF(mod);
  }
  PyGILState_Release(gil);
  return result;
}

void PD_DestroyPredictor(void* h) {
  if (!h) return;
  Predictor* p = static_cast<Predictor*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
}

// Runs the predictor on one float32 input; writes up to max_elems outputs.
// Returns number of output elements, or -1 on error.
int64_t PD_PredictorRunFloat(void* h, const float* data, const int64_t* shape,
                             int ndim, float* out_buf, int64_t max_elems,
                             int64_t* out_shape, int max_out_dims,
                             int* out_ndim) {
  if (!h) {
    g_last_error = "null predictor";
    return -1;
  }
  Predictor* p = static_cast<Predictor*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t n_out = -1;

  do {
    int64_t n_in = checked_numel(shape, ndim);
    if (n_in < 0) {
      g_last_error = "invalid shape (non-positive or overflowing dims)";
      break;
    }
    // marshal via bytes (no per-element boxing; bridge uses np.frombuffer)
    PyObject* buf = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(data), n_in * sizeof(float));
    PyObject* shp = buf ? shape_list(shape, ndim) : nullptr;
    if (!buf || !shp) {
      set_error("allocation failed");
      Py_XDECREF(buf);
      Py_XDECREF(shp);
      break;
    }
    PyObject* helper = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
    if (!helper) {
      set_error("import capi_bridge failed");
      Py_DECREF(buf);
      Py_DECREF(shp);
      break;
    }
    PyObject* res =
        PyObject_CallMethod(helper, "run_float_bytes", "OOO", p->obj, buf, shp);
    Py_DECREF(helper);
    Py_DECREF(buf);
    Py_DECREF(shp);
    if (!res) {
      set_error("predictor run failed");
      break;
    }
    // res = (bytes, shape_list)
    PyObject* out_bytes = PyTuple_GetItem(res, 0);
    PyObject* out_shp = PyTuple_GetItem(res, 1);
    char* raw = nullptr;
    Py_ssize_t raw_len = 0;
    if (!out_bytes || !out_shp ||
        PyBytes_AsStringAndSize(out_bytes, &raw, &raw_len) != 0) {
      set_error("malformed bridge result");
      Py_DECREF(res);
      break;
    }
    Py_ssize_t n = raw_len / static_cast<Py_ssize_t>(sizeof(float));
    Py_ssize_t nd = PyList_Size(out_shp);
    if (n > max_elems || nd > max_out_dims) {
      g_last_error = "output buffer too small";
      Py_DECREF(res);
      break;
    }
    std::memcpy(out_buf, raw, n * sizeof(float));
    for (Py_ssize_t i = 0; i < nd; ++i) {
      out_shape[i] = PyLong_AsLongLong(PyList_GetItem(out_shp, i));
    }
    *out_ndim = static_cast<int>(nd);
    n_out = static_cast<int64_t>(n);
    Py_DECREF(res);
  } while (false);

  PyGILState_Release(gil);
  return n_out;
}

// ---- training (reference paddle/fluid/train/demo/demo_trainer.cc) --------
//
// A standalone C host trains a Python-authored, jit.save'd model: params +
// optimizer state stay device-side inside the SpmdTrainer between calls;
// each PD_TrainStepFloat runs ONE cached jitted fwd+bwd+update step and
// returns only the scalar loss over the C boundary.

void* PD_CreateTrainer(const char* model_prefix, const char* optimizer,
                       double learning_rate, const char* loss) {
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* args = Py_BuildValue("(ssds)", model_prefix, optimizer,
                                 learning_rate, loss);
  if (!args) {
    set_error("allocation failed");
  } else {
    PyObject* t = call_bridge("paddle_tpu.inference.capi_train_bridge",
                              "create_trainer", args);
    Py_DECREF(args);
    if (t) result = new Trainer{t, 0.0};
  }
  PyGILState_Release(gil);
  return result;
}

void PD_DestroyTrainer(void* h) {
  if (!h) return;
  Trainer* t = static_cast<Trainer*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(t->obj);
  PyGILState_Release(gil);
  delete t;
}

// One train step: x float32, y int64 labels (or float32 targets when
// y_is_float != 0, e.g. mse). Returns 0 and stores the loss (PD_GetLoss),
// or -1 (PD_GetLastError).
int PD_TrainStepFloat(void* h, const float* x, const int64_t* x_shape,
                      int x_ndim, const void* y, const int64_t* y_shape,
                      int y_ndim, int y_is_float) {
  if (!h) {
    g_last_error = "null trainer";
    return -1;
  }
  Trainer* t = static_cast<Trainer*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;

  do {
    int64_t nx = checked_numel(x_shape, x_ndim);
    int64_t ny = checked_numel(y_shape, y_ndim);
    if (nx < 0 || ny < 0) {
      g_last_error = "invalid shape (non-positive or overflowing dims)";
      break;
    }
    PyObject* xb = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(x), nx * sizeof(float));
    PyObject* yb = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(y),
        ny * (y_is_float ? sizeof(float) : sizeof(int64_t)));
    PyObject* xs = shape_list(x_shape, x_ndim);
    PyObject* ys = shape_list(y_shape, y_ndim);
    if (!xb || !yb || !xs || !ys) {
      set_error("allocation failed");
      Py_XDECREF(xb);
      Py_XDECREF(yb);
      Py_XDECREF(xs);
      Py_XDECREF(ys);
      break;
    }
    PyObject* args = PyTuple_Pack(6, t->obj, xb, xs, yb, ys,
                                  y_is_float ? Py_True : Py_False);
    Py_DECREF(xb);
    Py_DECREF(yb);
    Py_DECREF(xs);
    Py_DECREF(ys);
    if (!args) {
      set_error("allocation failed");
      break;
    }
    PyObject* res = call_bridge("paddle_tpu.inference.capi_train_bridge",
                                "train_step_bytes", args);
    Py_DECREF(args);
    if (!res) break;
    double loss = PyFloat_AsDouble(res);
    Py_DECREF(res);
    if (PyErr_Occurred()) {
      // keep last_loss at the most recent SUCCESSFUL step's value
      set_error("non-scalar loss");
      break;
    }
    t->last_loss = loss;
    rc = 0;
  } while (false);

  PyGILState_Release(gil);
  return rc;
}

double PD_GetLoss(void* h) {
  if (!h) return 0.0;
  return static_cast<Trainer*>(h)->last_loss;
}

// Persist the trained parameters at `prefix` (jit.save fallback format —
// PD_CreatePredictor/jit.load then serve the trained weights). 0 = ok.
int PD_TrainerSave(void* h, const char* prefix) {
  if (!h) {
    g_last_error = "null trainer";
    return -1;
  }
  Trainer* t = static_cast<Trainer*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* args = Py_BuildValue("(Os)", t->obj, prefix);
  if (!args) {
    set_error("allocation failed");
  } else {
    PyObject* res = call_bridge("paddle_tpu.inference.capi_train_bridge",
                                "save_params", args);
    Py_DECREF(args);
    if (res) {
      rc = 0;
      Py_DECREF(res);
    }
  }
  PyGILState_Release(gil);
  return rc;
}

}  // extern "C"
