// C inference API — native shim over the paddle_tpu predictor.
//
// Reference parity: paddle/fluid/inference/capi/ (PD_NewAnalysisConfig,
// PD_NewPredictor, PD_PredictorRun, paddle_c_api.h) — the C surface that the Go
// (go/paddle/predictor.go) and R clients wrap.
//
// TPU-native design: the predictor's execution engine is XLA reached through
// Python (jit.load -> jax), so the C ABI embeds the CPython interpreter rather
// than re-implementing a runtime: each call acquires the GIL (PyGILState) and
// drives paddle_tpu.inference. Inside an existing Python process (the test
// path, and any embedder that already runs Python) the resident interpreter is
// reused; standalone C hosts get one via Py_Initialize.
//
// API (see native/paddle_tpu_capi.h):
//   PD_Init() / PD_Finalize()
//   PD_CreatePredictor(model_prefix)        -> handle (0 on failure)
//   PD_PredictorRunFloat(h, in, shape, ndim, out_buf, out_shape, max_*)
//   PD_DestroyPredictor(h)
//   PD_GetLastError()                       -> thread-local message
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

thread_local std::string g_last_error;

void set_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  g_last_error = where;
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      g_last_error += ": ";
      g_last_error += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Predictor {
  PyObject* obj;  // paddle_tpu TranslatedLayer / Predictor callable
};

}  // namespace

extern "C" {

const char* PD_GetLastError() { return g_last_error.c_str(); }

int PD_Init() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) return -1;
    // Release the GIL held by the initializing thread: callers (the Go
    // client migrates goroutines across OS threads) reach the interpreter
    // via PyGILState_Ensure, which deadlocks if the init thread keeps the
    // GIL forever. Saving the thread state here makes every later call —
    // from ANY OS thread, including this one — go through PyGILState.
    PyEval_SaveThread();
  }
  return 0;
}

void PD_Finalize() {
  // no-op when embedded in a live Python process; standalone hosts may call
  // Py_FinalizeEx themselves once all predictors are destroyed
}

void* PD_CreatePredictor(const char* model_prefix) {
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.jit");
  if (!mod) {
    set_error("import paddle_tpu.jit failed");
  } else {
    PyObject* loaded =
        PyObject_CallMethod(mod, "load", "s", model_prefix);
    if (!loaded) {
      set_error("jit.load failed");
    } else {
      Predictor* p = new Predictor{loaded};
      result = p;
    }
    Py_DECREF(mod);
  }
  PyGILState_Release(gil);
  return result;
}

void PD_DestroyPredictor(void* h) {
  if (!h) return;
  Predictor* p = static_cast<Predictor*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
}

// Runs the predictor on one float32 input; writes up to max_elems outputs.
// Returns number of output elements, or -1 on error.
int64_t PD_PredictorRunFloat(void* h, const float* data, const int64_t* shape,
                             int ndim, float* out_buf, int64_t max_elems,
                             int64_t* out_shape, int max_out_dims,
                             int* out_ndim) {
  if (!h) {
    g_last_error = "null predictor";
    return -1;
  }
  Predictor* p = static_cast<Predictor*>(h);
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t n_out = -1;

  do {
    if (ndim <= 0 || ndim > 16) {
      g_last_error = "invalid ndim";
      break;
    }
    int64_t n_in = 1;
    bool bad = false;
    for (int i = 0; i < ndim; ++i) {
      if (shape[i] <= 0 || n_in > (int64_t{1} << 40) / (shape[i] + 1)) {
        bad = true;
        break;
      }
      n_in *= shape[i];
    }
    if (bad) {
      g_last_error = "invalid shape (non-positive or overflowing dims)";
      break;
    }
    // marshal via bytes (no per-element boxing; bridge uses np.frombuffer)
    PyObject* buf = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(data), n_in * sizeof(float));
    PyObject* shp = buf ? PyList_New(ndim) : nullptr;
    if (!buf || !shp) {
      set_error("allocation failed");
      Py_XDECREF(buf);
      Py_XDECREF(shp);
      break;
    }
    bool shp_ok = true;
    for (int i = 0; i < ndim; ++i) {
      PyObject* v = PyLong_FromLongLong(shape[i]);
      if (!v) {
        shp_ok = false;
        break;
      }
      PyList_SET_ITEM(shp, i, v);
    }
    if (!shp_ok) {
      set_error("allocation failed");
      Py_DECREF(buf);
      Py_DECREF(shp);
      break;
    }
    PyObject* helper = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
    if (!helper) {
      set_error("import capi_bridge failed");
      Py_DECREF(buf);
      Py_DECREF(shp);
      break;
    }
    PyObject* res =
        PyObject_CallMethod(helper, "run_float_bytes", "OOO", p->obj, buf, shp);
    Py_DECREF(helper);
    Py_DECREF(buf);
    Py_DECREF(shp);
    if (!res) {
      set_error("predictor run failed");
      break;
    }
    // res = (bytes, shape_list)
    PyObject* out_bytes = PyTuple_GetItem(res, 0);
    PyObject* out_shp = PyTuple_GetItem(res, 1);
    char* raw = nullptr;
    Py_ssize_t raw_len = 0;
    if (!out_bytes || !out_shp ||
        PyBytes_AsStringAndSize(out_bytes, &raw, &raw_len) != 0) {
      set_error("malformed bridge result");
      Py_DECREF(res);
      break;
    }
    Py_ssize_t n = raw_len / static_cast<Py_ssize_t>(sizeof(float));
    Py_ssize_t nd = PyList_Size(out_shp);
    if (n > max_elems || nd > max_out_dims) {
      g_last_error = "output buffer too small";
      Py_DECREF(res);
      break;
    }
    std::memcpy(out_buf, raw, n * sizeof(float));
    for (Py_ssize_t i = 0; i < nd; ++i) {
      out_shape[i] = PyLong_AsLongLong(PyList_GetItem(out_shp, i));
    }
    *out_ndim = static_cast<int>(nd);
    n_out = static_cast<int64_t>(n);
    Py_DECREF(res);
  } while (false);

  PyGILState_Release(gil);
  return n_out;
}

}  // extern "C"
