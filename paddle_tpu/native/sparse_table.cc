// Sparse parameter-server table — native C++ engine.
//
// Reference parity: paddle/fluid/distributed/table/common_sparse_table.cc
// (auto-growing id -> row store with fill-on-miss initialization) with the
// server-side optimizer rules of table/depends/sparse.h (sum/sgd/adagrad/adam
// applied where the parameters live, so workers ship gradients, not weights).
//
// TPU-native design: the PS tier is host-side by construction (SURVEY.md §2.3 —
// embedding tables larger than HBM live on hosts; only pulled rows enter device
// memory), so this is plain C++ — an open-addressing hash (linear probing,
// power-of-two capacity) over one contiguous row pool:
//     row layout = [dim value floats][slot floats (adagrad: dim; adam: 2*dim+2)]
// Batch pull/push loop in C++ at -O3; duplicate ids within a push merge first
// (the reference merges by id before applying the rule). Row init is a
// per-id-seeded xorshift uniform so values are deterministic regardless of
// insertion order or thread count.
//
// extern "C" API (ctypes-consumed; no pybind11 in the image):
//   pst_create(dim, opt_id, lr, init_scale, seed)   -> handle
//      opt_id: 0=sum 1=sgd 2=adagrad 3=adam
//   pst_pull(h, ids, n, out)                        out: [n, dim] f32
//   pst_push(h, ids, n, grads)                      grads: [n, dim] f32
//   pst_size(h)                                     -> row count
//   pst_keys(h, out_ids)                            fills all ids (size() int64)
//   pst_get_rows(h, ids, n, out)                    pull without init-on-miss
//                                                   (missing rows -> zeros)
//   pst_save(h, path) / pst_load(h, path)           binary snapshot
//   pst_destroy(h)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

enum Opt { OPT_SUM = 0, OPT_SGD = 1, OPT_ADAGRAD = 2, OPT_ADAM = 3 };

struct Table {
  int dim;
  int opt;
  float lr;
  float init_scale;
  uint64_t seed;
  int row_stride;   // dim + slot floats
  // open addressing: buckets hold index+1 into rows (0 = empty)
  std::vector<uint64_t> bucket_key;
  std::vector<uint32_t> bucket_val;
  std::vector<float> rows;       // row-major pool, row_stride per row
  std::vector<uint64_t> ids;     // rowIdx -> id
  size_t count = 0;
  std::mutex mu;

  int slot_floats() const {
    switch (opt) {
      case OPT_ADAGRAD: return dim;
      case OPT_ADAM: return 2 * dim + 2;
      default: return 0;
    }
  }
};

inline uint64_t mix(uint64_t x) {
  x ^= x >> 33; x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33; x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33; return x;
}

void grow(Table* t);

uint32_t find_or_insert(Table* t, uint64_t key, bool* inserted) {
  if ((t->count + 1) * 10 > t->bucket_key.size() * 7) grow(t);
  size_t mask = t->bucket_key.size() - 1;
  size_t i = mix(key) & mask;
  while (true) {
    if (t->bucket_val[i] == 0) {
      uint32_t idx = static_cast<uint32_t>(t->count++);
      t->bucket_key[i] = key;
      t->bucket_val[i] = idx + 1;
      *inserted = true;
      return idx;
    }
    if (t->bucket_key[i] == key) {
      *inserted = false;
      return t->bucket_val[i] - 1;
    }
    i = (i + 1) & mask;
  }
}

// lookup only; returns UINT32_MAX when absent
uint32_t find(const Table* t, uint64_t key) {
  size_t mask = t->bucket_key.size() - 1;
  size_t i = mix(key) & mask;
  while (true) {
    if (t->bucket_val[i] == 0) return UINT32_MAX;
    if (t->bucket_key[i] == key) return t->bucket_val[i] - 1;
    i = (i + 1) & mask;
  }
}

void grow(Table* t) {
  size_t ncap = t->bucket_key.size() * 2;
  std::vector<uint64_t> nk(ncap, 0);
  std::vector<uint32_t> nv(ncap, 0);
  size_t mask = ncap - 1;
  for (size_t i = 0; i < t->bucket_key.size(); ++i) {
    if (t->bucket_val[i] == 0) continue;
    size_t j = mix(t->bucket_key[i]) & mask;
    while (nv[j] != 0) j = (j + 1) & mask;
    nk[j] = t->bucket_key[i];
    nv[j] = t->bucket_val[i];
  }
  t->bucket_key.swap(nk);
  t->bucket_val.swap(nv);
}

float* row_ptr(Table* t, uint32_t idx) {
  size_t need = (static_cast<size_t>(idx) + 1) * t->row_stride;
  if (t->rows.size() < need) t->rows.resize(need, 0.f);
  if (t->ids.size() <= idx) t->ids.resize(idx + 1, 0);
  return t->rows.data() + static_cast<size_t>(idx) * t->row_stride;
}

void init_row(Table* t, uint64_t id, float* row) {
  // per-id xorshift: deterministic under any insertion order
  uint64_t s = mix(t->seed ^ mix(id)) | 1ULL;
  for (int d = 0; d < t->dim; ++d) {
    s ^= s << 13; s ^= s >> 7; s ^= s << 17;
    // 24-bit mantissa uniform in [0, 1)
    float u = static_cast<float>((s >> 40) & 0xFFFFFF) / 16777216.0f;
    row[d] = (2.0f * u - 1.0f) * t->init_scale;
  }
  float* slots = row + t->dim;
  int ns = t->slot_floats();
  for (int k = 0; k < ns; ++k) slots[k] = 0.f;
  if (t->opt == OPT_ADAM) {           // beta1_pow / beta2_pow start at 1
    slots[2 * t->dim] = 1.0f;
    slots[2 * t->dim + 1] = 1.0f;
  }
}

void apply_rule(Table* t, float* row, const float* grad) {
  const int dim = t->dim;
  float* slots = row + dim;
  switch (t->opt) {
    case OPT_SUM:
      for (int d = 0; d < dim; ++d) row[d] -= grad[d];
      break;
    case OPT_SGD:
      for (int d = 0; d < dim; ++d) row[d] -= t->lr * grad[d];
      break;
    case OPT_ADAGRAD:
      for (int d = 0; d < dim; ++d) {
        slots[d] += grad[d] * grad[d];
        row[d] -= t->lr * grad[d] / (std::sqrt(slots[d]) + 1e-6f);
      }
      break;
    case OPT_ADAM: {
      const float b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
      float* m = slots;
      float* v = slots + dim;
      float& b1p = slots[2 * dim];
      float& b2p = slots[2 * dim + 1];
      b1p *= b1;
      b2p *= b2;
      for (int d = 0; d < dim; ++d) {
        m[d] = b1 * m[d] + (1 - b1) * grad[d];
        v[d] = b2 * v[d] + (1 - b2) * grad[d] * grad[d];
        float mhat = m[d] / (1 - b1p);
        float vhat = v[d] / (1 - b2p);
        row[d] -= t->lr * mhat / (std::sqrt(vhat) + eps);
      }
      break;
    }
  }
}

}  // namespace

extern "C" {

void* pst_create(int dim, int opt_id, float lr, float init_scale, uint64_t seed) {
  Table* t = new Table();
  t->dim = dim;
  t->opt = opt_id;
  t->lr = lr;
  t->init_scale = init_scale;
  t->seed = seed;
  t->row_stride = dim + t->slot_floats();
  t->bucket_key.assign(1024, 0);
  t->bucket_val.assign(1024, 0);
  return t;
}

void pst_destroy(void* h) { delete static_cast<Table*>(h); }

int64_t pst_size(void* h) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->count);
}

void pst_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    bool inserted = false;
    uint32_t idx = find_or_insert(t, static_cast<uint64_t>(ids[i]), &inserted);
    float* row = row_ptr(t, idx);
    if (inserted) {
      t->ids[idx] = static_cast<uint64_t>(ids[i]);
      init_row(t, static_cast<uint64_t>(ids[i]), row);
    }
    std::memcpy(out + i * t->dim, row, sizeof(float) * t->dim);
  }
}

void pst_get_rows(void* h, const int64_t* ids, int64_t n, float* out) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    uint32_t idx = find(t, static_cast<uint64_t>(ids[i]));
    if (idx == UINT32_MAX) {
      std::memset(out + i * t->dim, 0, sizeof(float) * t->dim);
    } else {
      std::memcpy(out + i * t->dim,
                  t->rows.data() + static_cast<size_t>(idx) * t->row_stride,
                  sizeof(float) * t->dim);
    }
  }
}

void pst_push(void* h, const int64_t* ids, int64_t n, const float* grads) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  const int dim = t->dim;
  // merge duplicate ids first (reference merges by id before apply): O(n)
  std::unordered_map<int64_t, size_t> first;
  first.reserve(static_cast<size_t>(n));
  std::vector<int64_t> uniq;
  std::vector<float> merged;
  uniq.reserve(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    auto it = first.find(ids[i]);
    if (it == first.end()) {
      first.emplace(ids[i], uniq.size());
      uniq.push_back(ids[i]);
      merged.insert(merged.end(), grads + i * dim, grads + (i + 1) * dim);
    } else {
      float* dst = merged.data() + it->second * dim;
      const float* src = grads + i * dim;
      for (int d = 0; d < dim; ++d) dst[d] += src[d];
    }
  }
  for (size_t i = 0; i < uniq.size(); ++i) {
    bool inserted = false;
    uint32_t idx = find_or_insert(t, static_cast<uint64_t>(uniq[i]), &inserted);
    float* row = row_ptr(t, idx);
    if (inserted) {
      t->ids[idx] = static_cast<uint64_t>(uniq[i]);
      init_row(t, static_cast<uint64_t>(uniq[i]), row);
    }
    apply_rule(t, row, merged.data() + i * dim);
  }
}

void pst_keys(void* h, int64_t* out) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (size_t i = 0; i < t->count; ++i) out[i] = static_cast<int64_t>(t->ids[i]);
}

int pst_save(void* h, const char* path) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  int64_t header[4] = {static_cast<int64_t>(t->count), t->dim, t->opt,
                       t->row_stride};
  std::fwrite(header, sizeof(int64_t), 4, f);
  std::fwrite(t->ids.data(), sizeof(uint64_t), t->count, f);
  std::fwrite(t->rows.data(), sizeof(float),
              t->count * static_cast<size_t>(t->row_stride), f);
  std::fclose(f);
  return 0;
}

int pst_load(void* h, const char* path) {
  Table* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t header[4];
  if (std::fread(header, sizeof(int64_t), 4, f) != 4) { std::fclose(f); return -2; }
  if (header[1] != t->dim || header[2] != t->opt || header[3] != t->row_stride) {
    std::fclose(f);
    return -3;
  }
  size_t count = static_cast<size_t>(header[0]);
  t->ids.assign(count, 0);
  t->rows.assign(count * static_cast<size_t>(t->row_stride), 0.f);
  if (std::fread(t->ids.data(), sizeof(uint64_t), count, f) != count) {
    std::fclose(f); return -2;
  }
  size_t nfloats = count * static_cast<size_t>(t->row_stride);
  if (std::fread(t->rows.data(), sizeof(float), nfloats, f) != nfloats) {
    std::fclose(f); return -2;
  }
  std::fclose(f);
  // rebuild hash
  size_t cap = 1024;
  while (cap * 7 < count * 10) cap *= 2;
  t->bucket_key.assign(cap, 0);
  t->bucket_val.assign(cap, 0);
  t->count = 0;
  for (size_t i = 0; i < count; ++i) {
    bool ins = false;
    uint32_t idx = find_or_insert(t, t->ids[i], &ins);
    (void)idx;
  }
  t->count = count;
  return 0;
}

}  // extern "C"
