// MultiSlot data-feed parser — native C++ core of the dataset pipeline.
//
// Reference parity: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed /
// MultiSlotInMemoryDataFeed (data_feed.h:682) — parses the MultiSlot text format
//     <num_1> v v ... <num_2> v v ...        (one group per slot, per line)
// into per-slot ragged buffers, and data_set.cc Dataset's in-memory shuffle.
//
// TPU-native design: the parser fills contiguous host buffers (values + per-instance
// lengths) that Python turns into padded numpy batches for device_put — no LoDTensor;
// LoD lives only at this boundary (SURVEY.md "hard parts" #2). Multithreaded file
// parsing mirrors the reference's per-thread DataFeed channels.
//
// extern "C" API (ctypes-consumed; no pybind11 in the image):
//   msp_create(slot_types, n_slots)            -> handle
//   msp_parse_file(h, path, n_threads)         -> n_instances (appends)
//   msp_parse_buffer(h, data, len)             -> n_instances
//   msp_shuffle(h, seed)
//   msp_num_instances(h)
//   msp_slot_total_values(h, slot)             -> total value count for slot
//   msp_copy_slot(h, slot, float*|int64* out_vals, int64* out_lens)
//   msp_clear(h) / msp_destroy(h)

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct SlotData {
  // ragged: values_f or values_i + per-instance value counts
  std::vector<float> values_f;
  std::vector<int64_t> values_i;
  std::vector<int64_t> lengths;
  bool is_float = true;
};

struct Instance {
  // parsed single line: per-slot values
  std::vector<std::vector<float>> f;
  std::vector<std::vector<int64_t>> i;
};

struct Parser {
  std::vector<int> slot_types;  // 0 = float, 1 = int64
  std::vector<SlotData> slots;
  int64_t n_instances = 0;
  std::mutex mu;

  explicit Parser(const int* types, int n) : slot_types(types, types + n), slots(n) {
    for (int s = 0; s < n; ++s) slots[s].is_float = (slot_types[s] == 0);
  }
};

bool parse_line(const char* line, size_t len, const std::vector<int>& types,
                Instance* out) {
  const char* p = line;
  const char* end = line + len;
  out->f.assign(types.size(), {});
  out->i.assign(types.size(), {});
  for (size_t s = 0; s < types.size(); ++s) {
    char* next = nullptr;
    long n = strtol(p, &next, 10);
    if (next == p || n < 0) return false;
    p = next;
    if (types[s] == 0) {
      auto& v = out->f[s];
      v.reserve(n);
      for (long k = 0; k < n; ++k) {
        float x = strtof(p, &next);
        if (next == p) return false;
        v.push_back(x);
        p = next;
      }
    } else {
      auto& v = out->i[s];
      v.reserve(n);
      for (long k = 0; k < n; ++k) {
        long long x = strtoll(p, &next, 10);
        if (next == p) return false;
        v.push_back((int64_t)x);
        p = next;
      }
    }
    if (p > end) return false;
  }
  return true;
}

void append_instances(Parser* h, std::vector<Instance>& batch) {
  std::lock_guard<std::mutex> lock(h->mu);
  for (auto& inst : batch) {
    for (size_t s = 0; s < h->slots.size(); ++s) {
      auto& slot = h->slots[s];
      if (slot.is_float) {
        slot.values_f.insert(slot.values_f.end(), inst.f[s].begin(), inst.f[s].end());
        slot.lengths.push_back((int64_t)inst.f[s].size());
      } else {
        slot.values_i.insert(slot.values_i.end(), inst.i[s].begin(), inst.i[s].end());
        slot.lengths.push_back((int64_t)inst.i[s].size());
      }
    }
    h->n_instances++;
  }
  batch.clear();
}

int64_t parse_chunk(Parser* h, const std::vector<std::string>& lines, size_t begin,
                    size_t endi) {
  std::vector<Instance> local;
  local.reserve(endi - begin);
  Instance inst;
  int64_t ok = 0;
  for (size_t idx = begin; idx < endi; ++idx) {
    if (lines[idx].empty()) continue;
    if (parse_line(lines[idx].c_str(), lines[idx].size(), h->slot_types, &inst)) {
      local.push_back(std::move(inst));
      inst = Instance();
      ok++;
    }
  }
  append_instances(h, local);
  return ok;
}

}  // namespace

extern "C" {

void* msp_create(const int* slot_types, int n_slots) {
  return new Parser(slot_types, n_slots);
}

void msp_destroy(void* handle) { delete static_cast<Parser*>(handle); }

void msp_clear(void* handle) {
  auto* h = static_cast<Parser*>(handle);
  std::lock_guard<std::mutex> lock(h->mu);
  for (auto& s : h->slots) {
    s.values_f.clear();
    s.values_i.clear();
    s.lengths.clear();
  }
  h->n_instances = 0;
}

int64_t msp_parse_file(void* handle, const char* path, int n_threads) {
  auto* h = static_cast<Parser*>(handle);
  std::ifstream in(path);
  if (!in.good()) return -1;
  std::vector<std::string> lines;
  std::string line;
  while (std::getline(in, line)) lines.push_back(std::move(line));
  if (n_threads <= 1 || lines.size() < 1024) {
    return parse_chunk(h, lines, 0, lines.size());
  }
  std::vector<std::thread> threads;
  std::atomic<int64_t> total{0};
  size_t per = (lines.size() + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    size_t b = t * per;
    size_t e = std::min(lines.size(), b + per);
    if (b >= e) break;
    threads.emplace_back([&, b, e]() { total += parse_chunk(h, lines, b, e); });
  }
  for (auto& th : threads) th.join();
  return total.load();
}

int64_t msp_parse_buffer(void* handle, const char* data, int64_t len) {
  auto* h = static_cast<Parser*>(handle);
  std::vector<std::string> lines;
  const char* p = data;
  const char* end = data + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!nl) nl = end;
    lines.emplace_back(p, nl - p);
    p = nl + 1;
  }
  return parse_chunk(h, lines, 0, lines.size());
}

int64_t msp_num_instances(void* handle) {
  return static_cast<Parser*>(handle)->n_instances;
}

int64_t msp_slot_total_values(void* handle, int slot) {
  auto* h = static_cast<Parser*>(handle);
  auto& s = h->slots[slot];
  return s.is_float ? (int64_t)s.values_f.size() : (int64_t)s.values_i.size();
}

// copy slot data out: vals must hold slot_total_values, lens must hold n_instances
void msp_copy_slot_f(void* handle, int slot, float* vals, int64_t* lens) {
  auto* h = static_cast<Parser*>(handle);
  auto& s = h->slots[slot];
  memcpy(vals, s.values_f.data(), s.values_f.size() * sizeof(float));
  memcpy(lens, s.lengths.data(), s.lengths.size() * sizeof(int64_t));
}

void msp_copy_slot_i(void* handle, int slot, int64_t* vals, int64_t* lens) {
  auto* h = static_cast<Parser*>(handle);
  auto& s = h->slots[slot];
  memcpy(vals, s.values_i.data(), s.values_i.size() * sizeof(int64_t));
  memcpy(lens, s.lengths.data(), s.lengths.size() * sizeof(int64_t));
}

// Fisher-Yates over instance order, applied consistently to every slot
// (data_set.cc LocalShuffle parity).
void msp_shuffle(void* handle, uint64_t seed) {
  auto* h = static_cast<Parser*>(handle);
  std::lock_guard<std::mutex> lock(h->mu);
  int64_t n = h->n_instances;
  if (n <= 1) return;
  std::mt19937_64 rng(seed);
  std::vector<int64_t> perm(n);
  for (int64_t i = 0; i < n; ++i) perm[i] = i;
  for (int64_t i = n - 1; i > 0; --i) {
    std::uniform_int_distribution<int64_t> dist(0, i);
    std::swap(perm[i], perm[dist(rng)]);
  }
  for (auto& s : h->slots) {
    // offsets of each instance in the value stream
    std::vector<int64_t> offs(n + 1, 0);
    for (int64_t i = 0; i < n; ++i) offs[i + 1] = offs[i] + s.lengths[i];
    std::vector<int64_t> new_lens(n);
    if (s.is_float) {
      std::vector<float> nv(s.values_f.size());
      int64_t w = 0;
      for (int64_t i = 0; i < n; ++i) {
        int64_t src = perm[i];
        new_lens[i] = s.lengths[src];
        memcpy(nv.data() + w, s.values_f.data() + offs[src],
               s.lengths[src] * sizeof(float));
        w += s.lengths[src];
      }
      s.values_f.swap(nv);
    } else {
      std::vector<int64_t> nv(s.values_i.size());
      int64_t w = 0;
      for (int64_t i = 0; i < n; ++i) {
        int64_t src = perm[i];
        new_lens[i] = s.lengths[src];
        memcpy(nv.data() + w, s.values_i.data() + offs[src],
               s.lengths[src] * sizeof(int64_t));
        w += s.lengths[src];
      }
      s.values_i.swap(nv);
    }
    s.lengths.swap(new_lens);
  }
}

}  // extern "C"
