/* C inference API for paddle_tpu — header for external (C/Go/R) clients.
 *
 * Reference parity: paddle/fluid/inference/capi/paddle_c_api.h. Build the shim
 * with:  g++ -O2 -fPIC -shared $(python3-config --includes) -o libpaddle_tpu_capi.so capi.cc
 * Standalone (non-Python) hosts must also link $(python3-config --embed --ldflags).
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Initialize the runtime (embeds CPython when not already hosted). 0 = ok. */
int PD_Init(void);
void PD_Finalize(void);

/* Load a jit.save'd model by path prefix. NULL on failure (see PD_GetLastError). */
void* PD_CreatePredictor(const char* model_prefix);
void PD_DestroyPredictor(void* predictor);

/* Run on one float32 input tensor. Returns #output elements or -1 on error. */
int64_t PD_PredictorRunFloat(void* predictor, const float* data,
                             const int64_t* shape, int ndim, float* out_buf,
                             int64_t max_elems, int64_t* out_shape,
                             int max_out_dims, int* out_ndim);

const char* PD_GetLastError(void);

/* ---- training (reference paddle/fluid/train/demo/demo_trainer.cc) ----
 * Load a jit.save'd trainable Layer and train it from pure C: params and
 * optimizer state stay device-side between calls; each step runs one
 * cached jitted fwd+bwd+update and returns only the scalar loss.
 * optimizer: "sgd" | "momentum" | "adam" | "adamw";
 * loss: "cross_entropy" | "mse". NULL on failure (see PD_GetLastError). */
void* PD_CreateTrainer(const char* model_prefix, const char* optimizer,
                       double learning_rate, const char* loss);
void PD_DestroyTrainer(void* trainer);

/* One train step: x float32; y int64 labels, or float32 targets when
 * y_is_float != 0 (mse). Returns 0 (loss via PD_GetLoss) or -1. */
int PD_TrainStepFloat(void* trainer, const float* x, const int64_t* x_shape,
                      int x_ndim, const void* y, const int64_t* y_shape,
                      int y_ndim, int y_is_float);

/* Loss of the most recent successful PD_TrainStepFloat. */
double PD_GetLoss(void* trainer);

/* Persist trained params at prefix (servable via PD_CreatePredictor). */
int PD_TrainerSave(void* trainer, const char* prefix);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H_ */
