"""paddle.framework parity namespace."""
from ..core.generator import seed  # noqa: F401
from ..core.device import get_device, set_device  # noqa: F401
from . import io  # noqa: F401
from .io import load, save  # noqa: F401
