"""Persistent AOT executable cache: compile once per machine, not per process.

Every jit compile today is paid per-process — the Executor's jit cache
lives on the Program, SpmdTrainer rebuilds its step on the first
train_step, ServingEngine re-jits its whole program family on
construction. On real hardware those compiles cost minutes (NOTES_r5:
~26 min per probe), so a restarted server pays the full XLA optimization
bill before serving its first token. This module converts that into a
one-time cost: executables are lowered, compiled ONCE, serialized with
``jax.experimental.serialize_executable``, and content-addressed on disk;
every later process (same machine class, same jax) deserializes in
milliseconds instead of recompiling. Ahead-of-time specialization for
portability/efficiency is the Tensor Processing Primitives argument
(arXiv:2104.05755) applied at the executable level instead of the kernel
level.

Cache key: sha256 over the lowered StableHLO text (which already pins the
program, input avals, shardings, and donation), plus jax version, backend
platform + platform version, compile-relevant FLAGS (``use_bfloat16``,
``flash_attention_block``), and per-site extras (mesh topology
fingerprints, donation tuples, program labels).

Safety contract:

- ``FLAGS_jit_cache_dir`` unset (the default): NOTHING here runs — call
  sites get their plain ``jax.jit`` object back untouched; no lowering,
  no hashing, no disk I/O (tests/test_aot_cache_gate.py pins this).
- corrupt or stale entries (truncated file, different jax/platform
  version, undeserializable payload): silently evicted and recompiled —
  a bad cache file must never crash training or serving.
- a deserialized executable that rejects its first live call (layout or
  sharding drift the key missed) falls back to the plain jit for that
  signature and evicts the entry.
- writes are single-writer safe for concurrent processes: serialize to a
  private temp file, ``os.replace`` into place (atomic on POSIX).
- ``FLAGS_jit_cache_max_bytes`` caps the directory byte size with LRU
  eviction (mtime recency, bumped on every hit); the newest entry is
  always kept so one giant executable cannot disable its own cache.

Telemetry (paddle_tpu.monitor): the shared ``compile_cache_total`` family
carries a ``source`` label — ``memory`` (in-process hit), ``disk``
(deserialized from this cache), ``fresh`` (real XLA compile) — plus
``aot_serialize_ms``/``aot_deserialize_ms``/``aot_bytes`` histograms,
``aot_store_total{site,event}`` and ``aot_evict_total{reason}`` counters.

Warm-start entry points built on this module: ``Program.aot_compile``,
``SpmdTrainer.aot_build``, ``ServingEngine.warmup``, and the
``tools/aot_warm.py`` CLI (docs/AOT.md has the serve-deploy recipe).
"""
import os
import pickle
import time
import uuid

import numpy as np
import jax

from .. import flags as _flags
from .. import monitor as _monitor
# the dotted form FIRST: it imports the paddle_tpu.trace module (the
# package attribute may still be the paddle.trace math op at this point)
from ..trace import costs as _costs
from .. import trace as _trace
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from ..profiler import RecordEvent as _RecordEvent

__all__ = ["cache_dir", "enabled", "args_signature", "mesh_fingerprint",
           "compile_cached", "CachedJit", "cached_jit", "executable_of"]

_flags.define_flag(
    "jit_cache_dir", "",
    "persistent AOT executable cache directory shared across processes "
    "(framework/aot.py); empty = disabled: no lowering, hashing or disk "
    "I/O on any compile path")
_flags.define_flag(
    "jit_cache_max_bytes", 1 << 30,
    "LRU byte-size cap for FLAGS_jit_cache_dir (oldest entries evicted; "
    "the newest entry is always kept)")

_FORMAT = 1
_SUFFIX = ".aotx"

#: flags whose value changes what a trace produces without necessarily
#: changing the python call signature — part of every cache key
_KEYED_FLAGS = ("use_bfloat16", "flash_attention_block")

# the compile_cache_total/compile_total families are DECLARED by their
# call sites (static/, distributed/spmd.py) with matching labels; these
# handles resolve to the same registry metrics
_COMPILE_CACHE = _monitor.counter(
    "compile_cache_total",
    "jit-cache lookups by feed-signature (event: hit|miss; source: "
    "memory|disk|fresh)", labelnames=("site", "event", "sig", "source"))
_COMPILES = _monitor.counter(
    "compile_total", "fresh XLA compiles (disk/memory cache hits excluded)",
    labelnames=("site",))
_COMPILE_MS = _monitor.histogram(
    "compile_ms", "wall time to obtain an executable (fresh compile, or "
    "lower+deserialize on an AOT-cache hit)", labelnames=("site",))
_SER_MS = _monitor.histogram(
    "aot_serialize_ms", "executable serialize wall time",
    labelnames=("site",))
_DES_MS = _monitor.histogram(
    "aot_deserialize_ms", "executable deserialize wall time",
    labelnames=("site",))
_BYTES_BUCKETS = (1 << 10, 1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20,
                  1 << 22, 1 << 24, 1 << 26, 1 << 28, 1 << 30)
_AOT_BYTES = _monitor.histogram(
    "aot_bytes", "serialized executable entry size",
    labelnames=("site", "event"), buckets=_BYTES_BUCKETS)
_STORE_TOTAL = _monitor.counter(
    "aot_store_total", "cache-entry writes by outcome (ok|error); error = "
    "the executable could not be serialized/written (it still runs, the "
    "next process just recompiles)", labelnames=("site", "event"))
_EVICT_TOTAL = _monitor.counter(
    "aot_evict_total", "cache entries dropped (corrupt|version|lru) and "
    "executables disabled after rejecting a live call (call; also counts "
    "in-memory warmed executables with no disk entry)",
    labelnames=("reason",))


def record_compile(site, sig_label, source):
    """The ONE compile-cache telemetry mapping every site shares: a disk
    load is event=hit/source=disk; a memory hit is hit/memory; everything
    else (fresh compile, or the bypass path's lazy jit that will compile
    on first call) is miss/fresh and counts in compile_total."""
    if source == "memory":
        if _monitor.is_enabled():
            _COMPILE_CACHE.labels(site=site, event="hit", sig=sig_label,
                                  source="memory").inc()
        return
    # flight-recorder tag for every non-memory resolution: disk loads and
    # fresh compiles are exactly the events a wedged round asks about
    _blackbox.note("compile", site=site, sig=sig_label, source=source)
    if _monitor.is_enabled():
        _COMPILE_CACHE.labels(
            site=site, event="hit" if source == "disk" else "miss",
            sig=sig_label,
            source="disk" if source == "disk" else "fresh").inc()
    if source != "disk":
        _COMPILES.labels(site=site).inc()


def executable_of(fn):
    """The underlying XLA executable of a compile_cached/CachedJit
    result, or None for bypass results (a plain lazy jit has no
    executable to cost-account until its first call)."""
    if isinstance(fn, _GuardedCompiled):
        return fn._compiled
    return None


def cache_dir():
    """The configured cache directory, or '' when the cache is disabled."""
    return _flags.get_flag("jit_cache_dir", "") or ""


def enabled():
    return bool(cache_dir())


def args_signature(args):
    """Hashable per-call signature: the pytree structure plus every leaf's
    (shape, dtype, weak_type) — the same specialization key jax.jit uses,
    so one entry per compiled program. ShapeDtypeStructs sign identically
    to the real arrays they describe (warm() relies on this); non-array
    leaves (python scalars, traced weakly) sign by type only."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for x in leaves:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append((tuple(shape), str(dtype),
                          bool(getattr(x, "weak_type", False))))
        else:
            parts.append(("py", type(x).__name__))
    return treedef, tuple(parts)


def mesh_fingerprint(mesh):
    """Stable identity of a mesh's topology for cache keys: axis names and
    sizes, device kinds, device and process counts — an executable
    compiled for one topology must never be offered to another."""
    if mesh is None:
        return ("mesh", None)
    devs = list(np.asarray(mesh.devices).ravel())
    kinds = sorted({getattr(d, "device_kind", d.platform) for d in devs})
    return ("mesh", tuple(mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            tuple(kinds), len(devs), int(jax.process_count()))


def _canonical_specs(args):
    """Replace array leaves with ShapeDtypeStructs before lowering, so the
    lowered text (the cache key) is identical however the caller's arrays
    happen to be placed: a committed single-device array, an uncommitted
    eager result, and a warmup spec all lower to the same module. Only
    NamedShardings survive (they ARE program semantics — SPMD layouts);
    single-device/positional shardings are placement detail and dropped.
    Non-array leaves (python scalars) pass through and specialize weakly,
    exactly as a live call would."""
    from jax.sharding import NamedSharding

    def go(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        sh = getattr(x, "sharding", None)
        if not isinstance(sh, NamedSharding):
            sh = None
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sh,
                                    weak_type=bool(getattr(x, "weak_type",
                                                           False)))
    return jax.tree_util.tree_map(go, args)


def _backend():
    try:
        from jax.extend import backend as _jex_backend

        return _jex_backend.get_backend()
    except Exception:  # older jax: the private alias
        return jax.devices()[0].client


def _cache_key(lowered, extra_key=()):
    import hashlib

    be = _backend()
    h = hashlib.sha256()
    h.update(lowered.as_text().encode())
    h.update(jax.__version__.encode())
    h.update(f"{be.platform}:{be.platform_version}".encode())
    for name in _KEYED_FLAGS:
        h.update(f"{name}={_flags.get_flag(name)!r};".encode())
    for part in extra_key:
        h.update(repr(part).encode())
    return h.hexdigest()


def _entry_path(key):
    return os.path.join(cache_dir(), key + _SUFFIX)


class _StaleEntry(Exception):
    """Entry written by a different cache format / jax / platform."""


def _evict(path, reason):
    _EVICT_TOTAL.labels(reason=reason).inc()
    try:
        os.remove(path)
    except OSError:
        pass


def _load_entry(path, site):
    """Deserialize one cache entry; any failure evicts the file and
    returns None (silent recompile — never crash on a bad entry)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None  # plain miss
    t0 = time.perf_counter()
    try:
        # import inside the guard: a jax build without the serializer must
        # degrade to a silent recompile, not crash the compile path
        from jax.experimental.serialize_executable import \
            deserialize_and_load

        entry = pickle.loads(blob)
        be = _backend()
        if (not isinstance(entry, dict)
                or entry.get("format") != _FORMAT
                or entry.get("jax") != jax.__version__
                or entry.get("platform") != be.platform
                or entry.get("platform_version") != be.platform_version):
            raise _StaleEntry
        compiled = deserialize_and_load(entry["payload"], entry["in_tree"],
                                        entry["out_tree"])
    except Exception as e:
        _evict(path, "version" if isinstance(e, _StaleEntry) else "corrupt")
        return None
    if _monitor.is_enabled():
        _DES_MS.labels(site=site).observe((time.perf_counter() - t0) * 1e3)
        _AOT_BYTES.labels(site=site, event="deserialize").observe(len(blob))
    try:
        os.utime(path, None)  # LRU recency: a hit is a use
    except OSError:
        pass
    return compiled


def _store_entry(key, compiled, site):
    """Serialize `compiled` into the cache (atomic rename; never raises —
    a non-serializable executable still runs, the next process just
    recompiles) and enforce the LRU byte cap. Returns True on success."""
    d = cache_dir()
    tmp = None
    try:
        from jax.experimental.serialize_executable import serialize

        t0 = time.perf_counter()
        payload, in_tree, out_tree = serialize(compiled)
        be = _backend()
        blob = pickle.dumps(
            {"format": _FORMAT, "jax": jax.__version__,
             "platform": be.platform,
             "platform_version": be.platform_version,
             "site": site, "key": key, "payload": payload,
             "in_tree": in_tree, "out_tree": out_tree}, protocol=4)
        if _monitor.is_enabled():
            _SER_MS.labels(site=site).observe(
                (time.perf_counter() - t0) * 1e3)
            _AOT_BYTES.labels(site=site, event="serialize").observe(
                len(blob))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(
            d, f".tmp-{key[:16]}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, _entry_path(key))  # atomic: concurrent writers race
        tmp = None                         # benignly (same content per key)
        _STORE_TOTAL.labels(site=site, event="ok").inc()
        _enforce_lru(d)
        return True
    except Exception:
        _STORE_TOTAL.labels(site=site, event="error").inc()
        if tmp is not None:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return False


def _enforce_lru(d):
    """Evict oldest entries (mtime) until the directory fits the byte cap.
    The newest entry always survives — one oversized executable must not
    evict itself into a cache that can never hit."""
    cap = int(_flags.get_flag("jit_cache_max_bytes", 1 << 30))
    entries = []
    try:
        names = os.listdir(d)
    except OSError:
        return
    now = time.time()
    for name in names:
        p = os.path.join(d, name)
        if name.startswith(".tmp-"):
            # orphan from a crashed writer (killed between write and
            # rename): sweep once safely aged past any live write
            try:
                if now - os.stat(p).st_mtime > 3600:
                    os.remove(p)
            except OSError:
                pass
            continue
        if not name.endswith(_SUFFIX):
            continue
        try:
            st = os.stat(p)
        except OSError:
            continue
        entries.append((st.st_mtime, st.st_size, p))
    total = sum(size for _, size, _ in entries)
    entries.sort()
    for _, size, p in entries[:-1]:  # keep the newest no matter what
        if total <= cap:
            break
        try:
            os.remove(p)
        except OSError:
            continue
        total -= size
        _EVICT_TOTAL.labels(reason="lru").inc()


class _GuardedCompiled:
    """A cache-loaded (or spec-warmed) executable with a recompile escape
    hatch: if it rejects a live call — layout/sharding drift the key
    missed, machine-feature mismatch — evict the entry and hand the
    signature back to the plain jit instead of crashing the caller."""

    __slots__ = ("_compiled", "_jit", "_path")

    def __init__(self, compiled, jitted, path=None):
        self._compiled = compiled
        self._jit = jitted
        self._path = path

    def __call__(self, *args):
        compiled = self._compiled
        if compiled is None:
            return self._jit(*args)
        try:
            return compiled(*args)
        except (TypeError, ValueError):
            # pre-execution REJECTION only (signature/pytree/sharding
            # mismatch — raised before donation consumes any buffer):
            # drop the entry and fall back to the plain jit. Runtime
            # failures (XlaRuntimeError, OOM) propagate — retrying them
            # with already-donated inputs would destroy live state and
            # mask the real error.
            self._compiled = None
            if self._path is not None:
                _evict(self._path, "call")
            else:
                _EVICT_TOTAL.labels(reason="call").inc()
            return self._jit(*args)


def _goodput_compile():
    """`compile` wall-time attribution (FLAGS_goodput, ISSUE 20): a null
    context unless the goodput accountant is armed. Booked at THE
    compile chokepoint, so trainer AOT misses, serving warmups, and
    elastic resize warm-restarts all attribute — nested inside the
    trainer's `step` bucket, the compile time pauses it (exclusive
    buckets). One flag read per compile; the disarmed path never imports
    monitor/goodput.py (manifest-lazy)."""
    import contextlib

    if not _flags.get_flag("goodput", False):
        return contextlib.nullcontext()
    from ..monitor import goodput as _goodput

    return _goodput.bucket("compile")


def compile_cached(jitted, example_args, *, site, extra_key=(),
                   force=False):
    """Obtain an executable for ``jitted`` at ``example_args`` (real
    arrays, or jax.ShapeDtypeStructs for data-free warmup), through the
    on-disk cache when enabled.

    Returns ``(callable, source)``:

    - ``("bypass")`` — FLAGS_jit_cache_dir unset: ``jitted`` itself is
      returned untouched (no lowering, no disk I/O; jit compiles lazily
      on first call exactly as before). ``force=True`` — the warm-start
      APIs — compiles eagerly in memory instead, so warmup works without
      a cache dir (source ``fresh``, nothing written);
    - ``("disk")`` — deserialized from the cache;
    - ``("fresh")`` — lowered and compiled now, then serialized into the
      cache (best effort).

    Both non-bypass results are wrapped in a call-failure guard: an
    executable that rejects a live call (pytree/layout/sharding drift the
    key missed) falls back to the plain jit for good instead of crashing.
    """
    if not enabled():
        if not force:
            return jitted, "bypass"
        # the progress window brackets every eager XLA compile: a hung
        # compile leaves an ACTIVE, non-advancing aot/compile beacon for
        # the stall sentinel to name (monitor/blackbox.py)
        with _goodput_compile(), _blackbox.progress("aot/compile"):
            compiled = jitted.lower(
                *_canonical_specs(example_args)).compile()
        return _GuardedCompiled(compiled, jitted), "fresh"
    with _goodput_compile(), _blackbox.progress("aot/compile"):
        lowered = jitted.lower(*_canonical_specs(example_args))
        key = _cache_key(lowered, extra_key)
        compiled = _load_entry(_entry_path(key), site)
        if compiled is not None:
            return _GuardedCompiled(compiled, jitted,
                                    _entry_path(key)), "disk"
        compiled = lowered.compile()
        stored = _store_entry(key, compiled, site)
    # the guard knows the entry path so a call-rejected executable also
    # removes its own just-written file (a later process must not
    # deserialize a binary this one already proved uncallable)
    return _GuardedCompiled(compiled, jitted,
                            _entry_path(key) if stored else None), "fresh"


class CachedJit:
    """A ``jax.jit`` lookalike whose compilations go through the
    persistent cache: per call-signature, lower once, load-or-compile
    from disk, keep the executable in an in-process map. With
    FLAGS_jit_cache_dir unset and nothing warmed, every call delegates
    straight to the wrapped jit after one empty-dict + flag check —
    behavior and cost identical to plain jit (the tier-1 gate pins it).
    Once warmed/enabled, each call pays a python-level signature flatten
    over the arg pytrees (~µs for a params+KV-cache tree) — well under
    1% of a ms-scale decode step, but measurable; a latency-critical
    caller that truly has one static signature can hold the plain jit.

    ``warm(*specs)`` AOT-compiles one signature from
    ``jax.ShapeDtypeStruct`` specs (plus plain python scalars for
    weakly-typed args) without real data and without executing anything —
    the ServingEngine.warmup / SpmdTrainer.aot_build building block.
    """

    def __init__(self, fn=None, *, site, jit=None, label=None,
                 donate_argnums=(), sig_label=None, record_event=None,
                 extra_key=()):
        if jit is None:
            jit = jax.jit(fn, donate_argnums=donate_argnums)
        self._jit = jit
        self._site = site
        self._label = label or getattr(fn, "__name__", "jit")
        self._sig_label = sig_label  # callable(args) -> str, or None
        self._record_event = record_event or f"{site}/compile"
        self._extra_key = tuple(extra_key) + (self._label,)
        self._store = {}
        self._cost_entries = {}   # sig -> trace.costs entry (exact per
        #                           signature: bucketed families differ)
        # wrapper-LOCAL execution accounting: two engines sharing the
        # 'serving' site must not average each other's program flops
        # (callers are effectively single-threaded per wrapper; these are
        # observability counters, not the registry's locked metrics)
        self._exec_calls = 0
        self._exec_flops = 0.0

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def _label_of(self, args):
        return self._label if self._sig_label is None \
            else self._sig_label(args)

    def _compile(self, sig, args):
        with _RecordEvent(self._record_event), \
                _monitor.timed(_COMPILE_MS.labels(site=self._site)):
            # force: warm() without a cache dir still AOT-compiles in
            # memory (a warmed signature must never retrace at call time)
            compiled, source = compile_cached(
                self._jit, args, site=self._site,
                extra_key=self._extra_key, force=True)
        record_compile(self._site, self._label_of(args), source)
        # device cost registry: every executable this wrapper obtains —
        # fresh, warmed, or an AOT-cache deserialize hit — lands its
        # cost_analysis()/memory_analysis() under (site, program label);
        # the exact per-signature entry is also kept so executions of a
        # bucketed family account each bucket's own flops
        entry = _costs.record(self._site, self._label_of(args),
                              executable_of(compiled))
        if entry is not None:
            self._cost_entries[sig] = entry
        self._store[sig] = compiled
        return compiled

    def warm(self, *specs):
        """Compile one signature ahead of time from shape specs. Returns
        True if a compile (or disk load) happened, False if that
        signature was already warm."""
        sig = args_signature(specs)
        if sig in self._store:
            return False
        self._compile(sig, specs)
        return True

    def __call__(self, *args):
        store = self._store
        if not store and not enabled() and not _trace.is_enabled():
            return self._jit(*args)
        sig = args_signature(args)
        compiled = store.get(sig)
        if compiled is None:
            if not enabled() and not _trace.is_enabled():
                return self._jit(*args)  # warmed, but not for this sig
            # FLAGS_trace forces eager AOT (in memory when no cache dir)
            # so the cost registry sees an executable for every program
            compiled = self._compile(sig, args)
        else:
            record_compile(self._site, self._label_of(args), "memory")
        entry = self._cost_entries.get(sig)
        if entry is not None:   # wrapper-local: no lock on the hot path
            self._exec_calls += 1
            self._exec_flops += entry.get("flops", 0.0)
        return compiled(*args)

    def executed(self):
        """THIS wrapper's execution accounting: {"calls", "flops"} summed
        over every signature it dispatched (per-bucket exact). Empty
        until cost entries exist (FLAGS_trace / cache dir / warm())."""
        return {"calls": self._exec_calls, "flops": self._exec_flops}


def cached_jit(fn=None, **kwargs):
    """Factory form of :class:`CachedJit` (accepts ``jit=`` for an
    already-built jit object, e.g. a jit(shard_map(...)) wrapper)."""
    return CachedJit(fn, **kwargs)
