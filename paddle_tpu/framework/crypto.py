"""Model encryption — CipherFactory/AESCipher facade over the native AES core.

Reference parity: paddle/fluid/framework/io/crypto/ (cryptopp AESCipher,
cipher_utils key generation, pybind/crypto.cc bindings) — weights/programs are
encrypted at rest with a symmetric cipher. TPU build: AES-256-CTR implemented in
native/crypto_aes.cc (FIPS-197, no external deps); key derivation = PBKDF2-HMAC-SHA256
and integrity = HMAC-SHA256, both from the stdlib. Wire format:
    b"PTAE" | iv[16] | hmac[32] | ciphertext
The HMAC covers iv+ciphertext with a key derived separately from the passphrase.
"""
import ctypes
import hashlib
import hmac as hmac_mod
import os
import subprocess
import threading

_MAGIC = b"PTAE"
_LIB = None
_LIB_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(__file__), "..", "native", "crypto_aes.cc")
_SO = os.path.join(os.path.dirname(__file__), "..", "native", "_crypto_aes.so")


def _load_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is False:
            raise RuntimeError("native AES build failed previously")
        if _LIB is not None:
            return _LIB
        src = os.path.abspath(_SRC)
        so = os.path.abspath(_SO)
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", so,
                     src],
                    check=True, capture_output=True,
                )
        except (OSError, subprocess.CalledProcessError):
            _LIB = False
            raise
        lib = ctypes.CDLL(so)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.aes256_ctr_crypt.argtypes = [u8p, u8p, u8p, ctypes.c_uint64, u8p]
        _LIB = lib
        return lib


def _u8(b):
    return ctypes.cast(ctypes.create_string_buffer(b, len(b)),
                       ctypes.POINTER(ctypes.c_uint8))


def _derive_keys(key, salt=b"paddle-tpu-cipher"):
    """passphrase/bytes -> (enc_key[32], mac_key[32]) via PBKDF2-HMAC-SHA256."""
    if isinstance(key, str):
        key = key.encode()
    master = hashlib.pbkdf2_hmac("sha256", key, salt, 10000, dklen=64)
    return master[:32], master[32:]


def _ctr(enc_key, iv, data):
    lib = _load_lib()
    out = ctypes.create_string_buffer(len(data))
    lib.aes256_ctr_crypt(_u8(enc_key), _u8(iv), _u8(data), len(data),
                         ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)))
    return out.raw


class AESCipher:
    """framework/io/crypto/aes_cipher.cc parity: authenticated AES-256-CTR."""

    def __init__(self, key):
        self._enc_key, self._mac_key = _derive_keys(key)

    def encrypt(self, plaintext):
        iv = os.urandom(16)
        ct = _ctr(self._enc_key, iv, bytes(plaintext))
        tag = hmac_mod.new(self._mac_key, iv + ct, hashlib.sha256).digest()
        return _MAGIC + iv + tag + ct

    def decrypt(self, blob):
        blob = bytes(blob)
        if blob[:4] != _MAGIC:
            raise ValueError("not an encrypted paddle_tpu payload")
        iv, tag, ct = blob[4:20], blob[20:52], blob[52:]
        expect = hmac_mod.new(self._mac_key, iv + ct, hashlib.sha256).digest()
        if not hmac_mod.compare_digest(tag, expect):
            raise ValueError("decryption failed: wrong key or corrupted data")
        return _ctr(self._enc_key, iv, ct)

    def encrypt_to_file(self, plaintext, path):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path):
        with open(path, "rb") as f:
            return self.decrypt(f.read())


class CipherFactory:
    """pybind/crypto.cc CipherFactory parity."""

    @staticmethod
    def create_cipher(key=None, cipher_name="AESCipher"):
        if cipher_name != "AESCipher":
            raise ValueError(f"unknown cipher: {cipher_name}")
        return AESCipher(key if key is not None else CipherFactory.generate_key())

    @staticmethod
    def generate_key(nbytes=32):
        return os.urandom(nbytes)


def is_encrypted(path):
    with open(path, "rb") as f:
        return f.read(4) == _MAGIC
