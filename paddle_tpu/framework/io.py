"""paddle.save / paddle.load.

Reference parity: python/paddle/framework/io.py:202 save (pickled state_dict) / :292
load; fluid/dygraph/checkpoint.py:56 save_dygraph. Tensors are stored as numpy arrays
(bfloat16 kept via ml_dtypes view round-trip).

Durability (docs/ROBUSTNESS.md): ``save`` writes to a same-directory tmp
file and commits with ``os.replace`` — a crash mid-save can never leave a
partial file at the destination — and appends a sha256 integrity footer
that ``load`` verifies (bit rot / torn writes raise
:class:`CheckpointCorruptError` instead of unpickling garbage). Footerless
files written by older versions still load (unverified).
"""
import contextlib
import hashlib
import os
import pickle
import time

import numpy as np

from .. import flags as _flags
from .. import monitor as _monitor
from ..trace import costs as _costs  # noqa: F401  (imports the module)
from .. import trace as _trace
from ..core.tensor import Tensor
from ..profiler import RecordEvent as _RecordEvent
from ..testing import failpoints as _fp

# integrity footer: 8-byte magic + sha256(payload), appended after the
# pickled/encrypted payload. pickle stops at its STOP opcode, so a footer
# at the tail never confuses a reader that skips verification.
_FOOTER_MAGIC = b"PTSHA256"
_FOOTER_LEN = len(_FOOTER_MAGIC) + 32


class CheckpointCorruptError(RuntimeError):
    """The checkpoint file failed its integrity check (sha256 footer
    mismatch) or cannot be unpickled — truncated or corrupt write."""

_CKPT = _monitor.counter("checkpoint_total", "paddle.save/load calls",
                         labelnames=("op",))
_CKPT_MS = _monitor.histogram("checkpoint_ms", "save/load wall time",
                              labelnames=("op",))
_CKPT_BYTES = _monitor.counter("checkpoint_bytes_total",
                               "bytes written/read by paddle.save/load",
                               labelnames=("op",))


def _goodput_bucket(name):
    """ckpt_save/ckpt_restore wall-time attribution (FLAGS_goodput,
    ISSUE 20): a null context unless the goodput accountant is armed —
    one flag read per save/load, and the disarmed path never imports
    monitor/goodput.py (manifest-lazy). Booked HERE, at the one
    chokepoint every checkpoint byte passes, so CheckpointSaver,
    state_dict round-trips, and direct paddle.save/load all attribute."""
    if not _flags.get_flag("goodput", False):
        return contextlib.nullcontext()
    from ..monitor import goodput as _goodput

    return _goodput.bucket(name)


def _record_ckpt(op, path, t0, span=None):
    nbytes = None
    try:
        nbytes = os.path.getsize(path)
    except OSError:
        pass
    if span is not None:   # trace span tagged with the payload size
        span.end(path=path, **({} if nbytes is None else {"bytes": nbytes}))
    # flight-recorder byte tag (one boolean check when the recorder is
    # off): a checkpoint in flight at wedge time shows in the ring
    _monitor.bb_note("checkpoint", op=op, path=str(path), bytes=nbytes)
    if not _monitor.is_enabled():
        return
    _CKPT.labels(op=op).inc()
    _CKPT_MS.labels(op=op).observe((time.perf_counter() - t0) * 1e3)
    if nbytes is not None:
        _CKPT_BYTES.labels(op=op).inc(nbytes)
    _monitor.log_event("checkpoint", op=op, path=path)


def _pack(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data), "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_pack(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _unpack(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            t = Tensor(obj["data"])
            t.stop_gradient = obj.get("stop_gradient", True)
            t.name = obj.get("name", "")
            return t
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_unpack(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


class _HashingWriter:
    """File-object shim that feeds every written byte into a sha256 as the
    pickler streams, so the footer costs no second pass over the payload."""

    __slots__ = ("_f", "_h")

    def __init__(self, f, h):
        self._f = f
        self._h = h

    def write(self, b):
        self._h.update(b)
        return self._f.write(b)


def _reclaim_stale_tmps(path):
    """Remove ``<path>.tmp.<pid>`` leftovers from earlier crashed saves of
    the SAME destination whose writer process is gone — repeated crashes
    must not accumulate multi-GB tmp files. Live pids (another process —
    or thread — mid-save of this path) are left alone."""
    d = os.path.dirname(path) or "."
    prefix = os.path.basename(path) + ".tmp."
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        if not name.startswith(prefix):
            continue
        try:
            pid = int(name[len(prefix):])
            os.kill(pid, 0)
        except ValueError:
            continue            # not one of ours
        except ProcessLookupError:
            try:
                os.remove(os.path.join(d, name))
            except OSError:
                pass
        except OSError:
            continue            # e.g. EPERM: pid exists


def save(obj, path, protocol=4, **configs):
    """configs: encryption_key=<str|bytes> encrypts the payload at rest
    (framework/io/crypto parity, native AES-256-CTR + HMAC).

    Atomic + verified: the payload streams into ``<path>.tmp.<pid>``, gets
    a sha256 integrity footer, is fsync'd, and only then renames over
    `path` (directory entry fsync'd too). A crash at ANY point leaves
    either the old file or the new one — never a torn write — plus at
    worst a stale tmp file, which the next save of the same path reclaims
    once its writer pid is gone."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    _reclaim_stale_tmps(path)
    t0 = time.perf_counter()
    sp = _trace.start_span("checkpoint/save", subsystem="io")
    tmp = f"{path}.tmp.{os.getpid()}"
    with _goodput_bucket("ckpt_save"), _RecordEvent("checkpoint/save"):
        try:
            h = hashlib.sha256()
            with open(tmp, "wb") as f:
                w = _HashingWriter(f, h)
                key = configs.get("encryption_key")
                if key is not None:
                    from .crypto import AESCipher

                    w.write(AESCipher(key).encrypt(
                        pickle.dumps(_pack(obj), protocol=protocol)))
                else:  # streaming path: no full-payload copy in memory
                    pickle.dump(_pack(obj), w, protocol=protocol)
                # crash window under test: payload on disk, no footer, no
                # commit — the destination must stay untouched
                _fp.failpoint("ckpt/write")
                f.write(_FOOTER_MAGIC + h.digest())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic commit
            _fsync_dir(os.path.dirname(os.path.abspath(path)))
        except BaseException:
            # an error path reclaims its own tmp; a SIGKILL can't — the
            # CheckpointSaver startup sweep handles those
            try:
                os.remove(tmp)
            except OSError:
                pass
            sp.end(error=True)   # the failed save still leaves its span
            raise
    _record_ckpt("save", path, t0, span=sp)


def _fsync_dir(path):
    """fsync the directory entry so a just-committed rename survives power
    loss, completing the atomic-commit durability story. Best-effort: some
    filesystems refuse to open or fsync directories."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _verify_footer(f, path):
    """Verify the sha256 footer if present; returns (payload length,
    footer-verified?) and leaves the file position at 0. Footerless
    (pre-durability) files pass through unverified; a digest mismatch
    raises CheckpointCorruptError."""
    size = f.seek(0, os.SEEK_END)
    if size >= _FOOTER_LEN:
        f.seek(size - _FOOTER_LEN)
        tail = f.read(_FOOTER_LEN)
        if tail[:len(_FOOTER_MAGIC)] == _FOOTER_MAGIC:
            h = hashlib.sha256()
            f.seek(0)
            left = size - _FOOTER_LEN
            while left:
                chunk = f.read(min(1 << 20, left))
                if not chunk:
                    break
                h.update(chunk)
                left -= len(chunk)
            if h.digest() != tail[len(_FOOTER_MAGIC):]:
                raise CheckpointCorruptError(
                    f"{path}: integrity check failed — sha256 of the "
                    "payload does not match the footer (truncated or "
                    "corrupt write); restore from an older checkpoint")
            f.seek(0)
            return size - _FOOTER_LEN, True
    f.seek(0)
    return size, False


def load(path, **configs):
    from .crypto import _MAGIC

    key = configs.get("encryption_key")
    t0 = time.perf_counter()
    sp = _trace.start_span("checkpoint/load", subsystem="io")
    try:
        with _goodput_bucket("ckpt_restore"), \
                _RecordEvent("checkpoint/load"), open(path, "rb") as f:
            _fp.failpoint("ckpt/read")
            payload_len, verified = _verify_footer(f, path)
            if f.read(4) == _MAGIC:
                if key is None:
                    raise ValueError(
                        f"{path} is encrypted; pass encryption_key=")
                from .crypto import AESCipher

                f.seek(0)
                out = _unpack(pickle.loads(AESCipher(key).decrypt(
                    f.read(payload_len))))
                _record_ckpt("load", path, t0, span=sp)
                return out
            if key is not None:
                # caller expected an authenticated payload — a plain-pickle
                # file here means tampering or a save/load mismatch, not a
                # soft fallback
                raise ValueError(
                    f"encryption_key given but {path} is not encrypted "
                    "(magic header missing); refusing to load "
                    "unauthenticated data")
            f.seek(0)
            try:
                out = _unpack(pickle.load(f))
            except (pickle.UnpicklingError, EOFError, ValueError) as e:
                # AttributeError/MemoryError are deliberately NOT here:
                # they are as likely environmental (a class moved between
                # versions, OOM on a big state_dict) as corruption, and a
                # corrupt classification lets CheckpointSaver's fallback
                # walk DELETE the file — when ambiguous, propagate and
                # keep the data
                if verified:
                    # the sha256 footer proved the bytes are exactly what
                    # save wrote — this failure is environmental, NOT
                    # corruption
                    raise
                raise CheckpointCorruptError(
                    f"{path}: cannot unpickle checkpoint payload ({e}) — "
                    "the file is truncated or corrupt") from e
    except BaseException:
        sp.end(error=True)   # the failed load still leaves its span
        raise
    _record_ckpt("load", path, t0, span=sp)
    return out


def save_dygraph(state_dict, model_path):
    save(state_dict, model_path + (".pdparams" if not model_path.endswith(".pdparams") else ""))


def load_dygraph(model_path, **configs):
    params_path = model_path + ".pdparams"
    opt_path = model_path + ".pdopt"
    para = load(params_path, **configs) if os.path.exists(params_path) else None
    opt = load(opt_path, **configs) if os.path.exists(opt_path) else None
    return para, opt
