"""paddle.save / paddle.load.

Reference parity: python/paddle/framework/io.py:202 save (pickled state_dict) / :292
load; fluid/dygraph/checkpoint.py:56 save_dygraph. Tensors are stored as numpy arrays
(bfloat16 kept via ml_dtypes view round-trip).
"""
import os
import pickle
import time

import numpy as np

from .. import monitor as _monitor
from ..core.tensor import Tensor
from ..profiler import RecordEvent as _RecordEvent

_CKPT = _monitor.counter("checkpoint_total", "paddle.save/load calls",
                         labelnames=("op",))
_CKPT_MS = _monitor.histogram("checkpoint_ms", "save/load wall time",
                              labelnames=("op",))
_CKPT_BYTES = _monitor.counter("checkpoint_bytes_total",
                               "bytes written/read by paddle.save/load",
                               labelnames=("op",))


def _record_ckpt(op, path, t0):
    if not _monitor.is_enabled():
        return
    _CKPT.labels(op=op).inc()
    _CKPT_MS.labels(op=op).observe((time.perf_counter() - t0) * 1e3)
    try:
        _CKPT_BYTES.labels(op=op).inc(os.path.getsize(path))
    except OSError:
        pass
    _monitor.log_event("checkpoint", op=op, path=path)


def _pack(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data), "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_pack(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _unpack(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            t = Tensor(obj["data"])
            t.stop_gradient = obj.get("stop_gradient", True)
            t.name = obj.get("name", "")
            return t
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_unpack(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    """configs: encryption_key=<str|bytes> encrypts the payload at rest
    (framework/io/crypto parity, native AES-256-CTR + HMAC)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    t0 = time.perf_counter()
    with _RecordEvent("checkpoint/save"):
        key = configs.get("encryption_key")
        if key is not None:
            from .crypto import AESCipher

            payload = AESCipher(key).encrypt(pickle.dumps(_pack(obj),
                                                          protocol=protocol))
            with open(path, "wb") as f:
                f.write(payload)
        else:  # streaming path: no full-payload copy in memory
            with open(path, "wb") as f:
                pickle.dump(_pack(obj), f, protocol=protocol)
    _record_ckpt("save", path, t0)


def load(path, **configs):
    from .crypto import _MAGIC

    key = configs.get("encryption_key")
    t0 = time.perf_counter()
    with _RecordEvent("checkpoint/load"), open(path, "rb") as f:
        if f.read(4) == _MAGIC:
            if key is None:
                raise ValueError(f"{path} is encrypted; pass encryption_key=")
            from .crypto import AESCipher

            f.seek(0)
            out = _unpack(pickle.loads(AESCipher(key).decrypt(f.read())))
            _record_ckpt("load", path, t0)
            return out
        if key is not None:
            # caller expected an authenticated payload — a plain-pickle file
            # here means tampering or a save/load mismatch, not a soft fallback
            raise ValueError(
                f"encryption_key given but {path} is not encrypted "
                "(magic header missing); refusing to load unauthenticated data")
        f.seek(0)
        out = _unpack(pickle.load(f))
    _record_ckpt("load", path, t0)
    return out


def save_dygraph(state_dict, model_path):
    save(state_dict, model_path + (".pdparams" if not model_path.endswith(".pdparams") else ""))


def load_dygraph(model_path, **configs):
    params_path = model_path + ".pdparams"
    opt_path = model_path + ".pdopt"
    para = load(params_path, **configs) if os.path.exists(params_path) else None
    opt = load(opt_path, **configs) if os.path.exists(opt_path) else None
    return para, opt
