"""Weight-version lineage: a monotone identity for every weight state.

Every weight state the system trains or serves gets a
:class:`WeightVersion` stamp — ``(run_id, counter, origin)`` — minted by
:class:`SpmdTrainer` at construction, bumped on every optimizer step,
checkpoint restore, topology reshard, and serving hot-swap/adapter load
(ISSUE 20; the observable half of ROADMAP item 5's "sampler staleness
bounded and observable"). The stamp rides checkpoints as the
``__weight_version__`` leaf of ``CHECKPOINT_SCHEMA`` (pre-version
checkpoints load as version 0), train-step and ``stage_step`` spans as a
``weight_version`` attribute, and every served completion's
``Request.stats()``.

Deliberately tiny and dependency-free: the stamp is pure host metadata —
it never touches a compiled program, creates no metric series by itself
(the ``serving_weight_version`` gauge / ``serving_stale_sessions_total``
counter live in the manifest-lazy :mod:`paddle_tpu.monitor.goodput` and
only exist under ``FLAGS_goodput``), and is always on: armed and
disarmed runs mint identical versions, so parity is trivially preserved.
"""
import itertools
import os
import time

__all__ = ["ORIGINS", "WeightVersion", "new_run_id"]

#: where a version bump came from. ``init`` — trainer/engine
#: construction; ``step`` — one optimizer step; ``restore`` — a
#: same-topology checkpoint restore; ``reshard`` — a cross-topology
#: restore or a live resize(mesh); ``hot_swap`` — a serving engine
#: replaced its resident base weights in place; ``adapter_load`` — a
#: LoRA adapter landed in a serving slot.
ORIGINS = ("init", "step", "restore", "reshard", "hot_swap",
           "adapter_load")

_RUN_SEQ = itertools.count()


def new_run_id():
    """Mint a process-unique run id: pid + monotonic-ish time + a
    process-local sequence number — unique enough to join ledger rows,
    spans, and checkpoints of one run without any coordination."""
    return f"r{os.getpid():x}-{time.time_ns():x}-{next(_RUN_SEQ)}"


class WeightVersion:
    """One immutable weight-state identity. ``counter`` is monotone
    within a lineage: every mutation of the weights (step, restore,
    reshard, hot-swap) yields a strictly larger counter via
    :meth:`bump`, so "older than" is one integer compare."""

    __slots__ = ("run_id", "counter", "origin")

    def __init__(self, run_id, counter=0, origin="init"):
        if origin not in ORIGINS:
            raise ValueError(
                f"unknown weight-version origin {origin!r} — one of "
                f"{ORIGINS}")
        counter = int(counter)
        if counter < 0:
            raise ValueError(f"counter must be >= 0, got {counter}")
        self.run_id = str(run_id)
        self.counter = counter
        self.origin = origin

    def bump(self, origin):
        """The next version in this lineage (counter + 1) with the given
        origin; the receiver is unchanged (versions are immutable)."""
        return WeightVersion(self.run_id, self.counter + 1, origin)

    def to_dict(self):
        """The ``__weight_version__`` checkpoint-leaf form (plain data,
        pickles through framework/io.py unchanged)."""
        return {"run_id": self.run_id, "counter": self.counter,
                "origin": self.origin}

    @classmethod
    def from_dict(cls, d, run_id=None):
        """Inverse of :meth:`to_dict`. ``None`` / a malformed dict — a
        pre-version checkpoint — loads as version 0 (origin ``init``)
        under ``run_id``: the handoff-baseline contract that old
        checkpoints stay loadable."""
        if not isinstance(d, dict):
            return cls(run_id if run_id is not None else new_run_id(),
                       0, "init")
        try:
            return cls(d.get("run_id", run_id or new_run_id()),
                       d.get("counter", 0),
                       d.get("origin", "init"))
        except (TypeError, ValueError):
            return cls(run_id if run_id is not None else new_run_id(),
                       0, "init")

    def __str__(self):
        return f"{self.run_id}:{self.counter}:{self.origin}"

    def __repr__(self):
        return (f"WeightVersion(run_id={self.run_id!r}, "
                f"counter={self.counter}, origin={self.origin!r})")

    def __eq__(self, other):
        return (isinstance(other, WeightVersion)
                and self.run_id == other.run_id
                and self.counter == other.counter
                and self.origin == other.origin)

    def __hash__(self):
        return hash((self.run_id, self.counter, self.origin))
