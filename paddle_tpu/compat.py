"""paddle.compat parity (python/paddle/compat.py): py2/py3 helpers the fluid
API surface still references."""


def to_text(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, (bytes, bytearray)):
        return obj.decode(encoding)
    if isinstance(obj, list):
        return [to_text(o, encoding) for o in obj]
    if isinstance(obj, set):
        return {to_text(o, encoding) for o in obj}
    return str(obj) if not isinstance(obj, str) else obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, list):
        return [to_bytes(o, encoding) for o in obj]
    if isinstance(obj, set):
        return {to_bytes(o, encoding) for o in obj}
    return obj


def round(x, d=0):
    import builtins

    return builtins.round(x, d)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
