"""PyLayer: user-defined forward/backward (python/paddle/autograd/py_layer.py parity,
imperative/py_layer_fwd.h). TPU-native: the backward staticmethod becomes the recorded
pullback on the tape."""
from ..core.tape import Node, global_tape
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self._attrs = {}

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved

    def __setattr__(self, k, v):
        if k in ("_saved", "_attrs"):
            object.__setattr__(self, k, v)
        else:
            object.__setattr__(self, k, v)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tape = global_tape()
        with tape.pause():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        diff_inputs = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if tape.enabled and diff_inputs:
            input_positions = [i for i, a in enumerate(args) if isinstance(a, Tensor) and not a.stop_gradient]

            def pullback(cot_list):
                gs = [Tensor(c, stop_gradient=True) for c in cot_list]
                with tape.pause():
                    in_grads = cls.backward(ctx, *gs)
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = [in_grads]
                # map backward outputs (one per forward tensor arg) to diff inputs
                tensor_args = [a for a in args if isinstance(a, Tensor)]
                out_map = dict(zip((id(a) for a in tensor_args), in_grads))
                return tuple(
                    (out_map.get(id(t))._data if out_map.get(id(t)) is not None else None)
                    for t in diff_inputs
                )

            for o in outs:
                o.stop_gradient = False
            node = Node(diff_inputs, outs, pullback)
            for o in outs:
                o._node = node
            tape.record(node)
        return out if multi else outs[0]
