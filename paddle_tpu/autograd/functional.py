"""paddle.grad parity (python/paddle/fluid/dygraph/base.py grad() — the
PartialGradEngine path, imperative/partial_grad_engine.cc)."""
from ..core.tape import backward as _tape_backward
from ..core.tensor import Tensor


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    # save/restore .grad so paddle.grad doesn't pollute accumulated grads
    saved = [t.grad for t in inputs]
    saved_retain = [t.retain_grads for t in inputs]
    for t in inputs:
        t.grad = None
        t.retain_grads = True
    retain = retain_graph if retain_graph is not None else create_graph
    targets = {id(t) for t in inputs} if only_inputs else None
    _tape_backward(list(outputs), grad_outputs, retain_graph=bool(retain),
                   create_graph=bool(create_graph), targets=targets)
    grads = []
    for t, old, old_r in zip(inputs, saved, saved_retain):
        g = t.grad
        if g is None and not allow_unused:
            raise RuntimeError("a gradient is None; pass allow_unused=True to permit it")
        grads.append(g)
        t.grad = old
        t.retain_grads = old_r
    return grads
