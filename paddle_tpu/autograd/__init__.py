"""paddle.autograd parity (python/paddle/autograd/__init__.py): backward, grad,
no_grad, PyLayer (custom VJP)."""
from ..core.tape import no_grad  # noqa: F401
from .functional import grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    from ..core.tape import backward as _b

    _b(tensors, grad_tensors, retain_graph)
