"""GPT-2 style causal LM — the flagship model (BASELINE.json config #4: GPT-2 medium /
ERNIE-class pretraining).

Built entirely from paddle_tpu.nn; tensor-parallel variants use the distributed.split
layers so SpmdTrainer shards the matmuls over 'mp'. Attention goes through
F.scaled_dot_product_attention (Pallas flash kernel on TPU when shapes tile).

Reference parity: the reference trains ERNIE/GPT through fleet on the same Transformer
building blocks (python/paddle/nn/layer/transformer.py); there is no gpt model file in
the reference tree — this is the framework's own model zoo.
"""
import math
import re

import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F
from ..serving import decode_model as _decode_model


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
                 max_seq_len=1024, intermediate_size=None, dropout=0.1,
                 tensor_parallel=False, use_flash=True,
                 num_experts=0, moe_every=2, moe_k=2, moe_capacity_factor=2.0,
                 moe_aux_weight=0.01, moe_mesh=None,
                 sequence_parallel=False, sp_mesh=None, sp_impl="ring",
                 gelu_approx=False, attention_window=None,
                 num_kv_heads=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.dropout = dropout
        self.tensor_parallel = tensor_parallel
        self.use_flash = use_flash
        # tanh-approximate gelu (HF GPT-2's gelu_new); False = exact erf
        self.gelu_approx = gelu_approx
        # MoE (num_experts > 0 turns every `moe_every`-th block's MLP into a
        # MoELayer; moe_mesh with an 'ep' axis enables expert parallelism)
        if num_experts > 0 and not (1 <= moe_every <= num_layers):
            raise ValueError(f"moe_every={moe_every} must be in [1, num_layers="
                             f"{num_layers}] when num_experts > 0")
        if num_experts > 0 and tensor_parallel:
            # MoE expert weights are not mp-sharded; combining would silently
            # replicate the dominant parameter mass on every mp rank. Use
            # expert parallelism (moe_mesh with an 'ep' axis) instead.
            raise ValueError("num_experts > 0 with tensor_parallel=True is not "
                             "supported; shard experts with moe_mesh ('ep' axis)")
        self.num_experts = num_experts
        self.moe_every = moe_every
        self.moe_k = moe_k
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_aux_weight = moe_aux_weight
        self.moe_mesh = moe_mesh
        # long-context sequence parallelism (beyond-reference; SURVEY.md §5):
        # sp_mesh with an 'sp' axis shards attention over the sequence dim —
        # 'ring' rotates K/V blocks with ppermute, 'ulysses' all_to_alls
        # seq<->heads. Composes with dp on the same mesh.
        if sequence_parallel:
            if sp_mesh is None or "sp" not in sp_mesh.axis_names:
                raise ValueError("sequence_parallel=True needs sp_mesh with an "
                                 "'sp' axis (otherwise attention silently runs "
                                 "dense and defeats the sharding)")
            if dropout > 0:
                raise ValueError("sequence-parallel attention does not "
                                 "implement attention dropout; set dropout=0.0")
            sp_size = sp_mesh.shape["sp"]
            from ..distributed.long_context import VALID_SP_IMPLS

            if sp_impl not in VALID_SP_IMPLS:
                raise ValueError(f"sp_impl must be one of "
                                 f"{'|'.join(VALID_SP_IMPLS)}, got "
                                 f"{sp_impl!r}")
            if max_seq_len % sp_size != 0:
                raise ValueError(
                    f"sequence parallelism shards seq dim over sp={sp_size}: "
                    f"max_seq_len ({max_seq_len}) must divide evenly")
            if sp_impl.startswith("ulysses") and num_heads % sp_size != 0:
                raise ValueError(f"ulysses needs num_heads ({num_heads}) "
                                 f"divisible by sp={sp_size}")
            if sp_impl == "ring_flash":
                shard = max_seq_len // sp_size
                if max_seq_len % sp_size != 0 or shard % 128 != 0:
                    raise ValueError(
                        f"ring_flash needs the per-rank seq shard "
                        f"({max_seq_len}/{sp_size}={shard}) to be exact "
                        f"and a multiple of the 128 flash block")
            if sp_impl == "ulysses_flash" and max_seq_len % 128 != 0:
                raise ValueError("ulysses_flash needs the full seq "
                                 f"({max_seq_len}) to be a multiple of the "
                                 "128 flash block")
            if sp_impl.endswith("_flash") and \
                    (hidden_size // num_heads) % 64 != 0:
                raise ValueError(f"{sp_impl} needs head_dim % 64 == 0")
        self.sequence_parallel = sequence_parallel
        self.sp_mesh = sp_mesh
        self.sp_impl = sp_impl
        # sliding-window causal attention (Mistral-style): train AND decode
        # attend only to the last W tokens; flash block-skips out-of-band
        # pairs, the KV-cache decode masks the same band
        if attention_window is not None:
            import operator

            if isinstance(attention_window, bool):
                raise ValueError(f"attention_window must be a positive int, "
                                 f"got {attention_window!r}")
            try:
                attention_window = int(operator.index(attention_window))
            except TypeError:
                raise ValueError(
                    f"attention_window must be a positive int, got "
                    f"{attention_window!r}") from None
            if attention_window < 1:
                raise ValueError(f"attention_window must be a positive int, "
                                 f"got {attention_window!r}")
            if sequence_parallel:
                raise ValueError("attention_window does not compose with "
                                 "sequence_parallel yet")
        self.attention_window = attention_window
        # grouped-query attention (GQA): num_kv_heads < num_heads shares
        # each K/V head across a group of query heads — the KV cache (the
        # serving memory bound) shrinks by num_heads/num_kv_heads. Default
        # = num_heads (plain MHA, the packed qkv layout unchanged).
        num_kv_heads = num_kv_heads if num_kv_heads is not None else num_heads
        if (isinstance(num_kv_heads, bool)
                or not (1 <= num_kv_heads <= num_heads)
                or num_heads % num_kv_heads != 0):
            raise ValueError(
                f"num_kv_heads ({num_kv_heads!r}) must divide num_heads "
                f"({num_heads}) and lie in [1, num_heads]")
        if num_kv_heads != num_heads and tensor_parallel:
            raise ValueError("GQA with tensor_parallel layers is not "
                             "supported yet (KV-head sharding)")
        self.num_kv_heads = num_kv_heads

    @staticmethod
    def small():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def medium():
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16)

    @staticmethod
    def tiny():  # tests / dryrun
        return GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                         max_seq_len=128, dropout=0.0)


class GPTAttention(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        # GQA: K/V projections carry num_kv_heads heads; for plain MHA
        # (kv == heads) the packed layout is EXACTLY the historical
        # [h, 3h] — existing checkpoints load unchanged
        self.num_kv_heads = getattr(cfg, "num_kv_heads", cfg.num_heads)
        qkv_out = (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        self.use_flash = getattr(cfg, "use_flash", True)
        self.window = getattr(cfg, "attention_window", None)
        self.sp_mesh = cfg.sp_mesh if getattr(cfg, "sequence_parallel", False) else None
        self.sp_impl = getattr(cfg, "sp_impl", "ring")
        if cfg.tensor_parallel:
            from ..distributed.split import ColumnParallelLinear, RowParallelLinear

            self.qkv = ColumnParallelLinear(h, qkv_out)
            self.proj = RowParallelLinear(h, h)
        else:
            self.qkv = nn.Linear(h, qkv_out)
            self.proj = nn.Linear(h, h)
        self.dropout = cfg.dropout

    def forward(self, x):
        b, s, h = x.shape
        H, K, hd = self.num_heads, self.num_kv_heads, self.head_dim
        qkv = self.qkv(x)
        from ..tensor.manipulation import split as tsplit

        # boundary split [q | k | v]: identical to the historical
        # (3, H, hd) unpacking when K == H
        q, k, v = tsplit(qkv, [H * hd, K * hd, K * hd], axis=-1)
        q = q.reshape([b, s, H, hd])
        k = k.reshape([b, s, K, hd])
        v = v.reshape([b, s, K, hd])
        if K != H:
            # expand shared K/V heads across their query groups for the
            # dense/flash attention math (the cache-side decode keeps the
            # compact K heads — that is where GQA's memory win lives)
            from ..tensor.manipulation import repeat_interleave

            k = repeat_interleave(k, H // K, axis=2)
            v = repeat_interleave(v, H // K, axis=2)
        if self.sp_mesh is not None and "sp" in self.sp_mesh.axis_names:
            from ..core.dispatch import apply
            from ..distributed.long_context import sequence_parallel_attention

            # config validation covers max_seq_len; the RUNTIME seq must
            # satisfy the same constraints (shorter batches are routine)
            sp_size = self.sp_mesh.shape["sp"]
            if s % sp_size != 0:
                raise ValueError(f"seq {s} must divide over sp={sp_size}")
            if self.sp_impl == "ring_flash" and (s // sp_size) % 128 != 0:
                raise ValueError(
                    f"ring_flash needs the per-rank shard ({s}/{sp_size}="
                    f"{s // sp_size}) in 128-token flash blocks: pad the "
                    f"batch to a multiple of {128 * sp_size} or use "
                    f"sp_impl='ring'")
            if self.sp_impl == "ulysses_flash" and s % 128 != 0:
                raise ValueError(
                    f"ulysses_flash needs seq ({s}) in 128-token flash "
                    f"blocks: pad to a multiple of 128 or use "
                    f"sp_impl='ulysses'")
            out = apply(
                lambda qv, kv, vv: sequence_parallel_attention(
                    qv, kv, vv, self.sp_mesh, impl=self.sp_impl, causal=True),
                q, k, v)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.dropout if self.training else 0.0,
                training=self.training,
                use_flash=self.use_flash,
                window=self.window,
            )
        return self.proj(out.reshape([b, s, h]))


class GPTMLP(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        h, i = cfg.hidden_size, cfg.intermediate_size
        if cfg.tensor_parallel:
            from ..distributed.split import ColumnParallelLinear, RowParallelLinear

            self.fc1 = ColumnParallelLinear(h, i)
            self.fc2 = RowParallelLinear(i, h)
        else:
            self.fc1 = nn.Linear(h, i)
            self.fc2 = nn.Linear(i, h)
        self._gelu_approx = getattr(cfg, "gelu_approx", False)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=self._gelu_approx))


class GPTBlock(nn.Layer):
    def __init__(self, cfg, layer_idx=0):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        if cfg.num_experts > 0 and (layer_idx + 1) % cfg.moe_every == 0:
            self.mlp = nn.MoELayer(
                cfg.hidden_size, cfg.intermediate_size, cfg.num_experts,
                k=cfg.moe_k, capacity_factor=cfg.moe_capacity_factor,
                mesh=cfg.moe_mesh)
        else:
            self.mlp = GPTMLP(cfg)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        x = x + self.drop(self.attn(self.ln1(x)))
        y = self._tpp_mlp(x)
        if y is None:
            y = self.mlp(self.ln2(x))
        x = x + self.drop(y)
        return x

    def _tpp_mlp(self, x):
        """FLAGS_tpp_kernels (docs/PERF.md): route ln2+MLP through the
        TPP registry's ported ops — ln_matmul (the layernorm->matmul
        prologue) feeding the fused gelu+projection tail. One get_flag
        when disarmed; the registry module is only imported armed. None
        = dense fallback (flag unset, MoE/tensor-parallel MLPs, or
        shapes the registry can't tile). Kernel path needs functional
        autodiff (SpmdTrainer) — custom_vjp does not ride the eager
        tape, same restriction as every Pallas op here."""
        from .. import flags as _flags

        if not _flags.get_flag("tpp_kernels", False):
            return None
        from .. import nn as _nn

        if not isinstance(self.mlp, GPTMLP) \
                or not isinstance(self.mlp.fc1, _nn.Linear):
            return None
        from ..core.tensor import Tensor
        from ..ops import tpp

        out = tpp.gpt_block_mlp(x._data, self.ln2, self.mlp)
        return None if out is None else Tensor(out)


class GPTModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            from ..distributed.split import VocabParallelEmbedding

            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg, i) for i in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids):
        b, s = input_ids.shape
        import jax.numpy as jnp

        from ..tensor.creation import arange

        pos = arange(s, dtype="int32")  # int32: x64 is off on TPU/CPU — an "int64" request
        # is truncated with a per-call UserWarning (caught by the analysis trace-warnings gate)
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """LM head ties to wte (weight sharing, paddle GPT convention)."""

    def __init__(self, cfg):
        super().__init__()
        self.gpt = GPTModel(cfg)
        self.cfg = cfg

    def forward(self, input_ids):
        h = self.gpt(input_ids)
        if getattr(self, "lm_head", None) is not None:
            # untied head installed by pipeline_split: after pipelined training
            # the trained head lives here, not in wte
            return self.lm_head(h)
        # tied head: logits = h @ wte^T
        from ..tensor.math import matmul

        return matmul(h, self.gpt.wte.weight, transpose_y=True)

    def loss(self, input_ids, labels):
        logits = self.forward(input_ids)
        b, s, v = logits.shape
        loss = F.cross_entropy(logits.reshape([b * s, v]), labels.reshape([b * s]))
        aux = self.moe_aux_loss()
        if aux is not None:
            loss = loss + self.cfg.moe_aux_weight * aux
        return loss

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=None, top_p=None, seed=None, eos_token_id=None,
                 num_beams=1, length_penalty=1.0, dtype=None,
                 attention_mask=None, cache_dtype=None, tp_mesh=None):
        """Autoregressive decode with a KV cache, compiled as ONE program
        (prefill + lax.scan; static shapes, dynamic_update_slice cache).
        temperature=0 decodes greedily; otherwise samples — top_k keeps the
        k highest logits and top_p then applies nucleus filtering (smallest
        prefix reaching mass top_p; needs top_p < 1.0 to take effect).
        num_beams>1 runs beam search and returns a (sequences, scores)
        pair — the best beam per batch row plus its joint log-prob
        (PaddleNLP generate convention); sampling knobs (temperature/top_k/
        top_p) do not apply to beam search, which raises if they are set.
        Sequences are [b, prompt + max_new_tokens] ids including the prompt.
        cache_dtype='int8' quantizes the KV cache (per-row absmax scales) —
        half the bf16 cache's HBM traffic in the HBM-bound decode loop;
        composes with dtype='bfloat16' params.
        tp_mesh (a Mesh with an 'mp' axis) serves a DENSE model
        tensor-parallel: heads and the MLP inner dim shard over mp, the KV
        cache holds only local heads, two psums per layer ride the ICI —
        for models too big for one chip's HBM.
        See _gpt_generate/_gpt_beam_search for the TPU design notes."""
        if num_beams > 1:
            if top_p is not None or top_k is not None:
                raise ValueError(
                    "top_k/top_p are sampling knobs; beam search is "
                    "deterministic — drop them or use num_beams=1")
            return _gpt_beam_search(self, input_ids, max_new_tokens,
                                    num_beams, eos_token_id, length_penalty,
                                    dtype=dtype,
                                    attention_mask=attention_mask,
                                    cache_dtype=cache_dtype,
                                    tp_mesh=tp_mesh)
        return _gpt_generate(self, input_ids, max_new_tokens, temperature,
                             top_k, seed, eos_token_id, dtype=dtype,
                             attention_mask=attention_mask, top_p=top_p,
                             cache_dtype=cache_dtype, tp_mesh=tp_mesh)

    def generate_speculative(self, draft_model, input_ids,
                             max_new_tokens=32, k=4, dtype=None,
                             cache_dtype=None, tp_mesh=None,
                             eos_token_id=None):
        """Speculative greedy decoding with a small draft model: identical
        output to greedy `generate` (the acceptance rule is exact) but
        1..k+1 tokens per target forward. Returns (sequences, n_rounds) —
        n_rounds target forwards vs max_new_tokens single-token steps is
        the speedup headroom. Batch 1; greedy only. tp_mesh shards the
        TARGET over 'mp' (the draft stays replicated — it is small by
        design). See _gpt_speculative for the cache-invariant notes."""
        return _gpt_speculative(self, draft_model, input_ids,
                                max_new_tokens, k=k, dtype=dtype,
                                cache_dtype=cache_dtype, tp_mesh=tp_mesh,
                                eos_token_id=eos_token_id)

    def pipeline_split(self, pp_degree):
        """Split into (pre, stages, post_loss) for distributed.pipeline.
        PipelineTrainer. Unties the LM head (see GPTHeadLoss) and installs it
        as self.lm_head so forward()/state_dict() use the trained head after
        PipelineTrainer.sync_to_layer()."""
        return _gpt_pipeline_split(self, pp_degree)

    def moe_aux_loss(self):
        """Sum of MoE load-balance losses from the last forward (None if dense)."""
        aux = None
        for blk in self.gpt.blocks:
            a = getattr(blk.mlp, "aux_loss", None)
            if a is not None:
                aux = a if aux is None else aux + a
        return aux


class GPTPretrainLoss(nn.Layer):
    def forward(self, logits, labels):
        b, s, v = logits.shape
        return F.cross_entropy(logits.reshape([b * s, v]), labels.reshape([b * s]))


# ---------------------------------------------------------------------------
# Autoregressive decoding with a KV cache (the serving path).
# ---------------------------------------------------------------------------

def _cache_map(f, c):
    """Apply f to a cache leaf: a plain array, or an (int8 values, scales)
    pair. Keeps beam-search cache reshuffles codec-agnostic."""
    return tuple(f(x) for x in c) if isinstance(c, tuple) else f(c)


def _decode_fns(cfg, untied, untied_bias, cache_dtype=None, tp_axis=None,
                tp_size=1):
    """Pure-jnp decode math shared by sampling and beam search: returns
    (fwd, logits_of, cache_init). fwd(p, tok_ids [B, t], pos, kc, vc) runs
    the block stack with the KV cache [L, B, H, T, hd] (B is read from the
    input, so beam-expanded batches reuse the same functions).

    cache_dtype='int8' stores the cache as int8 values + per-row (over hd)
    f32 absmax scales, halving the HBM traffic of the cache reads that
    bound the decode loop even vs a bf16 cache; values dequantize blockwise
    into the attention einsums (XLA fuses the multiply into the read).
    cache_dtype='fp8' stores float8_e4m3fn at the same byte footprint —
    scaled casts keep a mantissa instead of integer rounding (native fp8
    on v5e+-class TPUs). No reference analog (the reference has no fused
    KV-cache decode at all) — these are the quantized-KV serving recipes
    from modern LLM inference stacks.

    tp_axis/tp_size: tensor-parallel serving inside shard_map — attention
    heads and the MLP inner dim are sharded over the mesh axis (Megatron
    column/row split), the KV cache holds only the local heads, and one
    psum after attn.proj + one after mlp.fc2 restore replicated
    activations. Param layout in this mode: qkv.weight [h, 3, H_loc, hd],
    qkv.bias [3, H_loc, hd] (see _tp_param_shard)."""
    import jax
    import jax.numpy as jnp

    L, Hh = cfg.num_layers, cfg.num_heads
    hd = cfg.hidden_size // Hh
    scale = 1.0 / math.sqrt(hd)
    # quantized cache formats: (storage dtype, qmax, integer rounding).
    # int8 rounds+clips to +-127; fp8 (e4m3fn, max ~448) just casts — the
    # per-row absmax scale puts values inside its representable range, and
    # the cast keeps a mantissa instead of rounding to integers (coarser
    # scale granularity, finer within-row resolution)
    _QUANT = {"int8": (jnp.int8, 127.0, True),
              "fp8": (jnp.float8_e4m3fn, 448.0, False)}
    if cache_dtype is not None and cache_dtype not in _QUANT:
        # the single interpreter of cache_dtype validates it for EVERY
        # entry point (generate, beam, speculative, ServingEngine) — a
        # typo must never silently serve a full-precision cache
        raise ValueError(
            f"cache_dtype must be None, 'int8', or 'fp8', "
            f"got {cache_dtype!r}")
    quant = _QUANT.get(cache_dtype)
    win = getattr(cfg, "attention_window", None)
    KVh = getattr(cfg, "num_kv_heads", Hh)  # GQA: compact K/V heads
    g = Hh // KVh                           # query heads per kv head
    H_loc = Hh // tp_size   # local q heads (== Hh when not tensor-parallel)
    KV_loc = KVh // tp_size  # (GQA+tp rejected at config: KVh==Hh under tp)

    def cache_init(b_, T_, dt):
        # the cache holds only the COMPACT kv heads — the GQA serving win
        shape = (L, b_, KV_loc, T_, hd)
        if quant is None:
            z = jnp.zeros(shape, dt)
            return z, jnp.zeros_like(z)
        vals = jnp.zeros(shape, quant[0])
        scales = jnp.zeros((L, b_, KV_loc, T_, 1), jnp.float32)
        return (vals, scales), (jnp.zeros_like(vals),
                                jnp.zeros_like(scales))

    def _row_update(cache_i, val, pos_vec):
        """Row b of `val` [B, KVh, t, hd] lands at its OWN column
        pos_vec[b] (continuous-batching serving: slots sit at different
        sequence positions)."""
        return jax.vmap(lambda row, v, p_: jax.lax.dynamic_update_slice(
            row, v, (0, p_, 0)))(cache_i, val, pos_vec)

    def _store(c, val, i, pos):
        per_row = jnp.ndim(pos) == 1
        if quant is None:
            if per_row:
                return c.at[i].set(_row_update(c[i], val, pos))
            return jax.lax.dynamic_update_slice(c, val[None],
                                                (i, 0, 0, pos, 0))
        qdt, qmax, integer = quant
        vals, scales = c
        s = jnp.maximum(
            jnp.max(jnp.abs(val), axis=-1, keepdims=True).astype(
                jnp.float32) / qmax, 1e-8)
        q = val.astype(jnp.float32) / s
        if integer:
            q = jnp.clip(jnp.round(q), -qmax, qmax)
        q = q.astype(qdt)
        if per_row:
            return (vals.at[i].set(_row_update(vals[i], q, pos)),
                    scales.at[i].set(_row_update(scales[i], s, pos)))
        return (jax.lax.dynamic_update_slice(vals, q[None], (i, 0, 0, pos, 0)),
                jax.lax.dynamic_update_slice(scales, s[None],
                                             (i, 0, 0, pos, 0)))

    def _load(c, i, like):
        if quant is None:
            return c[i]
        vals, scales = c
        return (vals[i].astype(jnp.float32) * scales[i]).astype(like)

    def ln(x, w, bb):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean((x - mu) ** 2, -1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * w + bb

    def block(p, i, x, kc, vc, pos, key_valid=None, lora=None):
        """x [B, t, h] whose first column sits at cache column `pos`.
        key_valid [B, T] (optional): False columns (left-pad slots) are
        masked out of every real query; a pad-position query still sees
        itself so its softmax row is never empty (its lane is garbage that
        no valid query ever reads).

        lora (optional, multi-LoRA serving): per-row ALREADY-GATHERED
        adapter factors — {"_scale": [B], kind: (A [B, L, din, r],
        B [B, L, r, dout])} for kind in qkv|proj|fc1|fc2. Each present
        kind's matmul grows a per-row low-rank delta
        ``(x @ A[:, i]) @ B[:, i] * scale`` batched over rows by ONE
        gathered einsum pair — no per-adapter program, no recompiles."""
        pre = f"gpt.blocks.{i}."
        bb, t = x.shape[0], x.shape[1]
        T = (kc[0] if isinstance(kc, tuple) else kc).shape[3]

        def _ldelta(xin, kind):
            A, Bm = lora[kind]
            d = jnp.einsum("bti,bir->btr", xin, A[:, i])
            d = jnp.einsum("btr,bro->bto", d, Bm[:, i])
            # adapter slot 0 is all-zero (base requests): the delta is an
            # exact-zero add in xin's dtype, never a dtype promotion
            return (d * lora["_scale"][:, None, None]).astype(xin.dtype)

        h_in = ln(x, p[pre + "ln1.weight"], p[pre + "ln1.bias"])
        if tp_axis is not None:
            # column-parallel qkv over LOCAL heads: weight [h, 3, H_loc, hd]
            qkv = jnp.einsum("bti,iknd->btknd",
                             h_in, p[pre + "attn.qkv.weight"]) \
                + p[pre + "attn.qkv.bias"]
            q = jnp.moveaxis(qkv[:, :, 0], 1, 2)      # [B, H_loc, t, hd]
            k = jnp.moveaxis(qkv[:, :, 1], 1, 2)
            v = jnp.moveaxis(qkv[:, :, 2], 1, 2)
        else:
            # boundary split [q | k | v] — identical to the historical
            # (3, H, hd) unpacking for MHA, compact kv heads for GQA
            flat = h_in @ p[pre + "attn.qkv.weight"] \
                + p[pre + "attn.qkv.bias"]
            if lora is not None and "qkv" in lora:
                flat = flat + _ldelta(h_in, "qkv")
            q = jnp.moveaxis(
                flat[..., :Hh * hd].reshape(bb, t, Hh, hd), 1, 2)
            k = jnp.moveaxis(
                flat[..., Hh * hd:(Hh + KVh) * hd].reshape(bb, t, KVh, hd),
                1, 2)
            v = jnp.moveaxis(
                flat[..., (Hh + KVh) * hd:].reshape(bb, t, KVh, hd), 1, 2)
        kc = _store(kc, k, i, pos)
        vc = _store(vc, v, i, pos)
        # causal over cache columns: query row r (column pos+r) sees cache
        # column c iff c <= pos + r. pos is a scalar (whole batch at one
        # frontier) or [B] (per-slot frontiers — continuous batching); one
        # mask construction serves both via a leading 1-or-B dim.
        pos_b = jnp.atleast_1d(pos)
        rows = pos_b[:, None, None] + jnp.arange(t)[None, :, None]
        cols = jnp.arange(T)[None, None, :]
        mask = cols <= rows                            # [1-or-B, t, T]
        if win is not None:  # sliding window: same band as training
            mask &= (rows - cols) < win
        if key_valid is not None:
            self_col = cols == rows                    # keep self: no NaN rows
            mask = mask & (key_valid[:, None, :] | self_col)
        if g == 1:
            att = jnp.einsum("bhtd,bhTd->bhtT", q,
                             _load(kc, i, q.dtype)) * scale
            att = jnp.where(mask[:, None], att, -jnp.inf)
            att = jax.nn.softmax(att, axis=-1)
            out = jnp.einsum("bhtT,bhTd->bhtd", att,
                             _load(vc, i, att.dtype))
        else:
            # grouped queries share their kv head: [B, KVh, g, t, *]
            qg = q.reshape(bb, KVh, g, t, hd)
            att = jnp.einsum("bkgtd,bkTd->bkgtT", qg,
                             _load(kc, i, q.dtype)) * scale
            att = jnp.where(mask[:, None, None], att, -jnp.inf)
            att = jax.nn.softmax(att, axis=-1)
            out = jnp.einsum("bkgtT,bkTd->bkgtd", att,
                             _load(vc, i, att.dtype)).reshape(
                                 bb, Hh, t, hd)
        out = jnp.moveaxis(out, 1, 2).reshape(bb, t, H_loc * hd)
        proj = out @ p[pre + "attn.proj.weight"]  # row-parallel under tp
        if lora is not None and "proj" in lora:
            proj = proj + _ldelta(out, "proj")
        if tp_axis is not None:
            proj = jax.lax.psum(proj, tp_axis)
        x = x + proj + p[pre + "attn.proj.bias"]
        h2 = ln(x, p[pre + "ln2.weight"], p[pre + "ln2.bias"])
        a1 = h2 @ p[pre + "mlp.fc1.weight"] + p[pre + "mlp.fc1.bias"]
        if lora is not None and "fc1" in lora:
            a1 = a1 + _ldelta(h2, "fc1")
        h2 = jax.nn.gelu(a1, approximate=getattr(cfg, "gelu_approx", False))
        mlp = h2 @ p[pre + "mlp.fc2.weight"]      # row-parallel under tp
        if lora is not None and "fc2" in lora:
            mlp = mlp + _ldelta(h2, "fc2")
        if tp_axis is not None:
            mlp = jax.lax.psum(mlp, tp_axis)
        x = x + mlp + p[pre + "mlp.fc2.bias"]
        return x, kc, vc

    def logits_of(p, x_last):
        h = ln(x_last, p["gpt.ln_f.weight"], p["gpt.ln_f.bias"])
        if untied:
            out = h @ p["lm_head.weight"]
            return out + p["lm_head.bias"] if untied_bias else out
        return h @ p["gpt.wte.weight"].T

    def fwd(p, tok_ids, pos, kc, vc, key_valid=None, pos_ids=None,
            lora=None, adapter_ids=None):
        t = tok_ids.shape[1]
        if pos_ids is None:
            if jnp.ndim(pos) == 1:   # per-row pos needs per-row pe too
                pos_ids = pos[:, None] + jnp.arange(t)[None, :]
                wpe = jnp.take(p["gpt.wpe.weight"], pos_ids, axis=0)
            else:
                wpe = jax.lax.dynamic_slice_in_dim(p["gpt.wpe.weight"],
                                                   pos, t)
        else:
            # ragged rows: per-row position ids (left-padding support)
            wpe = jnp.take(p["gpt.wpe.weight"], pos_ids, axis=0)
        x = jnp.take(p["gpt.wte.weight"], tok_ids, axis=0) + wpe
        lg = None
        if lora is not None:
            if tp_axis is not None:
                # the low-rank delta would need its own column/row split
                # and psum placement — unsupported rather than wrong
                raise ValueError(
                    "multi-LoRA decode is not supported under tensor-"
                    "parallel serving (tp_mesh); serve adapters dense")
            # ONE gather per step hoists every row's adapter factors out
            # of the layer loop: [S, L, din, r] -> [B, L, din, r]
            lg = {"_scale": lora["scale"][adapter_ids]}
            for kind in ("qkv", "proj", "fc1", "fc2"):
                if kind in lora:
                    lg[kind] = (lora[kind]["A"][adapter_ids],
                                lora[kind]["B"][adapter_ids])
        for i in range(L):
            x, kc, vc = block(p, i, x, kc, vc, pos, key_valid=key_valid,
                              lora=lg)
        return x, kc, vc

    return fwd, logits_of, cache_init


def _check_decode_config(cfg):
    if cfg.num_experts > 0 or cfg.sequence_parallel or cfg.tensor_parallel:
        raise ValueError(
            "generate() decodes dense single-replica configs; for parallel "
            "variants run the dense copy of the trained weights (state_dict "
            "round-trips) or use BeamSearchDecoder/dynamic_decode")


def _decode_compute_dtype(dtype):
    """None = f32 (exact); 'bfloat16'/'float16' = low-precision serving:
    params and the KV cache cast down (the decode loop is HBM-bound, so the
    cache halving is the win); logits always pick in f32."""
    if dtype is None:
        return None
    import jax.numpy as jnp

    from ..core import dtype as dtype_mod

    d = dtype_mod.convert_dtype(dtype)
    if not jnp.issubdtype(d, jnp.floating):
        raise ValueError(f"generate dtype must be floating, got {dtype!r}")
    if d == jnp.float32:
        return None  # the default path already IS f32 — avoid a dup compile
    return d


def _decode_setup(model, input_ids, max_new_tokens):
    import jax.numpy as jnp

    cfg = model.cfg
    _check_decode_config(cfg)
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    b, s0 = ids.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    T = s0 + max_new_tokens
    if T > cfg.max_seq_len:
        raise ValueError(f"prompt {s0} + max_new_tokens {max_new_tokens} "
                         f"exceeds max_seq_len {cfg.max_seq_len}")
    untied, untied_bias, params = _decode_params(model, "the model")
    return cfg, ids, b, s0, T, untied, untied_bias, params


def _decode_params(model, who):
    """Name-addressed param snapshot for the decode programs + the shared
    un-merged-LoRA guard and untied-head detection."""
    untied = getattr(model, "lm_head", None) is not None
    params = {n: p._data for n, p in model.named_parameters()}
    if any(".lora_A" in n for n in params):  # any wrap site, any Linear
        raise ValueError(
            f"decoding reads name-addressed params and {who} has un-merged "
            "LoRA adapters: call paddle_tpu.incubate.lora.merge_lora on it "
            "before generating, or use the eager forward for sampling "
            "during fine-tuning")
    # pipeline_split installs the head with bias_attr=False: no bias param
    untied_bias = untied and "lm_head.bias" in params
    return untied, untied_bias, params


def _tp_param_shard(params, cfg):
    """Reshape the packed qkv params for head-sharded serving and build the
    per-name PartitionSpecs (Megatron column/row split). Returns
    (params, specs): qkv.weight [h, 3h] -> [h, 3, H, hd] sharded on H;
    proj/fc2 row-split with the matching psum in the decode block; biases
    of row-parallel layers stay replicated (added once after the psum)."""
    from jax.sharding import PartitionSpec as P

    h, Hh = cfg.hidden_size, cfg.num_heads
    hd = h // Hh
    out, specs = {}, {}
    for n, v in params.items():
        if n.endswith("attn.qkv.weight"):
            v = v.reshape(h, 3, Hh, hd)
            specs[n] = P(None, None, "mp", None)
        elif n.endswith("attn.qkv.bias"):
            v = v.reshape(3, Hh, hd)
            specs[n] = P(None, "mp", None)
        elif n.endswith("attn.proj.weight"):
            specs[n] = P("mp", None)
        elif n.endswith("mlp.fc1.weight"):
            specs[n] = P(None, "mp")
        elif n.endswith("mlp.fc1.bias"):
            specs[n] = P("mp")
        elif n.endswith("mlp.fc2.weight"):
            specs[n] = P("mp", None)
        else:
            specs[n] = P()  # ln/embeddings/head + row-parallel biases
        out[n] = v
    return out, specs


def _tp_setup(tp_mesh, cfg, params):
    """Shared tensor-parallel serving setup: validates the mesh/config and
    reshapes+specs the params. Returns (tp_axis, tp_size, params, specs)."""
    if "mp" not in tp_mesh.axis_names:
        raise ValueError("tp_mesh needs an 'mp' axis")
    if getattr(cfg, "num_kv_heads", cfg.num_heads) != cfg.num_heads:
        raise ValueError("GQA tensor-parallel serving is not supported yet "
                         "(KV-head sharding); serve dense or use MHA")
    tp_size = tp_mesh.shape["mp"]
    Hh, inter = cfg.num_heads, cfg.intermediate_size
    if Hh % tp_size != 0 or inter % tp_size != 0:
        raise ValueError(
            f"tensor-parallel serving needs num_heads ({Hh}) and the "
            f"MLP inner dim ({inter}) divisible by mp={tp_size}")
    params, specs = _tp_param_shard(params, cfg)
    return "mp", tp_size, params, specs


def _tp_wrap(run, tp_mesh, tp_specs, n_extra_in, out_specs, in_specs=None,
             donate=()):
    """jit(shard_map(run)) for TP serving: params sharded per tp_specs and
    the n_extra_in trailing args replicated — or fully explicit in_specs
    (the serving engine passes its head-sharded cache specs); `donate`
    forwards to jit (in-place cache updates). Owns the shard_map
    import/check_vma version dance in ONE place."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    if in_specs is None:
        in_specs = (tp_specs,) + (P(),) * n_extra_in
    try:
        mapped = _sm(run, mesh=tp_mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)
    except TypeError:
        # older jax spells the knob check_rep; the check must actually be
        # OFF either way — replication inference has no rule for the
        # decode loop's while/scan carries (beam search, speculative),
        # and falling back to a CHECKING shard_map turns those decodes
        # into trace-time errors (the PR 17 clean-HEAD TP failures)
        try:
            mapped = _sm(run, mesh=tp_mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
        except TypeError:  # no replication checking in this jax at all
            mapped = _sm(run, mesh=tp_mesh, in_specs=in_specs,
                         out_specs=out_specs)
    return jax.jit(mapped, donate_argnums=donate)


def _gpt_generate(model, input_ids, max_new_tokens, temperature, top_k,
                  seed, eos_token_id, dtype=None, attention_mask=None,
                  top_p=None, cache_dtype=None, tp_mesh=None):
    """TPU-native autoregressive decode: ONE jitted program — prefill plus a
    lax.scan over decode steps against a static-shape KV cache updated with
    dynamic_update_slice. No per-step retrace, no dynamic shapes; the decode
    math is a pure-jnp mirror of the dense layer stack (parity against the
    cache-free full forward is pinned by tests/test_gpt_generate.py).

    Reference analog: the reference serves decoding via BeamSearchDecoder/
    dynamic_decode (which this framework also has); a fused single-program
    KV-cache loop is the TPU-idiomatic form."""
    import jax
    import jax.numpy as jnp

    cfg, ids, b, s0, T, untied, untied_bias, params = _decode_setup(
        model, input_ids, max_new_tokens)
    L, Hh = cfg.num_layers, cfg.num_heads
    hd = cfg.hidden_size // Hh
    tp_axis, tp_size, tp_specs = None, 1, None
    if tp_mesh is not None:
        tp_axis, tp_size, params, tp_specs = _tp_setup(tp_mesh, cfg, params)
    fwd, logits_of, cache_init = _decode_fns(cfg, untied, untied_bias,
                                             cache_dtype=cache_dtype,
                                             tp_axis=tp_axis,
                                             tp_size=tp_size)
    compute_dtype = _decode_compute_dtype(dtype)
    mask = _left_pad_mask(attention_mask, b, s0)

    def pick(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, -1).astype(jnp.int32)
        lg = logits / temperature
        if top_k is not None and top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        if top_p is not None and top_p < 1.0:
            # nucleus: keep the smallest prefix of the sorted distribution
            # whose mass reaches top_p (the top token always survives)
            srt = jnp.sort(lg, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            k_keep = jnp.sum(cum - probs < top_p, axis=-1)     # [b]
            cutoff = jnp.take_along_axis(
                srt, jnp.maximum(k_keep - 1, 0)[:, None], axis=-1)
            lg = jnp.where(lg < cutoff, -jnp.inf, lg)
        return jax.random.categorical(key, lg).astype(jnp.int32)

    def run(p, ids_, key, mask_):
        if compute_dtype is not None:
            # serving precision: bf16 params + bf16 KV cache (half the HBM
            # traffic the decode loop is bound by); logits pick in f32
            p = {k: (v.astype(compute_dtype)
                     if jnp.issubdtype(v.dtype, jnp.floating) else v)
                 for k, v in p.items()}
        kc, vc = cache_init(b, T, compute_dtype or jnp.float32)
        lens, key_valid, pos_ids = _ragged_setup(mask_, b, s0, T)
        x, kc, vc = fwd(p, ids_, 0, kc, vc, key_valid=key_valid,
                        pos_ids=pos_ids)
        tok = pick(logits_of(p, x[:, -1]).astype(jnp.float32), key)
        done = jnp.zeros((b,), bool) if eos_token_id is None else \
            (tok == eos_token_id)

        def step(carry, i):
            tok, kc, vc, key, done = carry
            key, sub = jax.random.split(key)
            # the fed token is the (i-1)-th generated one: cache column
            # s0 + i - 1; its POSITION id is per-row (len_i + i - 1) when
            # the batch is ragged
            step_pos = None if lens is None else \
                (lens + (i - 1))[:, None]
            x, kc, vc = fwd(p, tok[:, None], s0 + i - 1, kc, vc,
                            key_valid=key_valid, pos_ids=step_pos)
            nxt = pick(logits_of(p, x[:, 0]).astype(jnp.float32), sub)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            return (nxt, kc, vc, key, done), tok

        (last, *_), toks = jax.lax.scan(
            step, (tok, kc, vc, key, done), jnp.arange(1, max_new_tokens))
        return jnp.concatenate([toks.T, last[:, None]], axis=1) \
            if max_new_tokens > 1 else tok[:, None]

    cache_key = (b, s0, max_new_tokens, float(temperature), top_k,
                 eos_token_id, untied, untied_bias, str(compute_dtype),
                 mask is not None, None if top_p is None else float(top_p),
                 cache_dtype,
                 # the Mesh itself (hashable): same-size but different
                 # meshes must not reuse each other's shard_map closure
                 ("tp", tp_mesh) if tp_mesh is not None else None)
    store = model.__dict__.setdefault("_generate_compiled", {})
    if cache_key not in store:
        if tp_mesh is None:
            store[cache_key] = jax.jit(run)
        else:
            from jax.sharding import PartitionSpec as P

            store[cache_key] = _tp_wrap(run, tp_mesh, tp_specs, 3, P())
    if temperature == 0.0:
        key = jax.random.key(0)  # greedy never samples: don't advance the
        # global generator (reproducibility side effect otherwise)
    elif seed is not None:
        key = jax.random.key(seed)
    else:
        from ..core.generator import default_generator

        key = default_generator().split()
    out = store[cache_key](params, ids, key, mask)
    full = jnp.concatenate([ids.astype(out.dtype), out], axis=1)
    return Tensor(full)


def _gpt_speculative(model, draft_model, input_ids, max_new_tokens, k=4,
                     dtype=None, cache_dtype=None, tp_mesh=None,
                     eos_token_id=None):
    """Speculative GREEDY decoding (beyond reference): a small draft model
    proposes k tokens per round; the target verifies all k in ONE forward
    and accepts the longest matching prefix plus its own fix-up token, so
    each round costs k tiny draft steps + one (k+1)-token target step yet
    emits 1..k+1 tokens. Greedy acceptance makes the output equal to the
    target model's own greedy decode whatever the draft quality (up to XLA
    reassociation flipping argmax on exact logit ties — the multi-token
    verify forward and generate()'s single-token steps can round near-ties
    differently; tests pin equality on the test models). The whole loop is
    one jitted lax.while_loop program (trip count is data-dependent:
    better drafts finish in fewer rounds).

    Cache invariant per round: both KV caches hold the accepted prefix
    [0, pos); `cur` is the last accepted token not yet fed. The round feeds
    [cur, p0..p_{k-1}] (target) so stale columns beyond the accepted prefix
    are never read (causal mask) and are overwritten by later rounds.

    Scope: batch 1, greedy only. eos_token_id stops the loop once the
    accepted slice contains eos, filling the tail with eos exactly like
    the dense scan's done-mask — fewer rounds on early termination."""
    import jax
    import jax.numpy as jnp

    cfg, ids, b, s0, T0, untied, untied_bias, params = _decode_setup(
        model, input_ids, max_new_tokens)
    if b != 1:
        raise ValueError(f"speculative decoding is batch-1 (got batch {b}); "
                         "run rows separately, use generate(), or serve "
                         "batches speculatively via inference.serving."
                         "ServingEngine(draft_model=...)")
    if draft_model.cfg.vocab_size != cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    if not (1 <= k <= 16):
        raise ValueError(f"k must be in [1, 16], got {k}")
    if s0 < 2:
        raise ValueError("speculative decoding needs a prompt of >= 2 tokens")
    _check_decode_config(draft_model.cfg)
    d_cfg = draft_model.cfg
    T = s0 + max_new_tokens + k + 1  # writes can run k past the accepted end
    if T > cfg.max_seq_len or T > d_cfg.max_seq_len:
        raise ValueError(
            f"prompt {s0} + max_new_tokens {max_new_tokens} + draft window "
            f"{k + 1} exceeds a max_seq_len ({cfg.max_seq_len} target, "
            f"{d_cfg.max_seq_len} draft)")
    d_untied, d_untied_bias, params_d = _decode_params(draft_model,
                                                       "the draft model")

    tp_axis, tp_size, tp_specs = None, 1, None
    if tp_mesh is not None:
        # target shards over mp; the (small) draft stays replicated
        tp_axis, tp_size, params, tp_specs = _tp_setup(tp_mesh, cfg, params)
    fwd_t, logits_t, cache_init_t = _decode_fns(cfg, untied, untied_bias,
                                                cache_dtype=cache_dtype,
                                                tp_axis=tp_axis,
                                                tp_size=tp_size)
    fwd_d, logits_d, cache_init_d = _decode_fns(d_cfg, d_untied,
                                                d_untied_bias,
                                                cache_dtype=cache_dtype)
    compute_dtype = _decode_compute_dtype(dtype)

    def run(pt, pd, ids_):
        if compute_dtype is not None:
            cast = lambda p: {n: (v.astype(compute_dtype)
                                  if jnp.issubdtype(v.dtype, jnp.floating)
                                  else v) for n, v in p.items()}
            pt, pd = cast(pt), cast(pd)
        kc_t, vc_t = cache_init_t(1, T, compute_dtype or jnp.float32)
        kc_d, vc_d = cache_init_d(1, T, compute_dtype or jnp.float32)
        # prefill both caches with the prompt MINUS its last token; that
        # last token is `cur` (fed at the head of each round)
        prefix = ids_[:, :s0 - 1]
        _, kc_t, vc_t = fwd_t(pt, prefix, 0, kc_t, vc_t)
        _, kc_d, vc_d = fwd_d(pd, prefix, 0, kc_d, vc_d)
        cur = ids_[:, s0 - 1]                              # [1]
        out_buf = jnp.zeros((1, max_new_tokens + k + 1), jnp.int32)

        eos = -1 if eos_token_id is None else int(eos_token_id)

        def round_body(carry):
            (pos, cur, emitted, out_buf, kc_t, vc_t, kc_d, vc_d, rounds,
             done) = carry
            # --- draft proposes k tokens (k single-token forwards) -------
            props = []
            d_cur = cur
            for j in range(k):
                xd, kc_d, vc_d = fwd_d(pd, d_cur[:, None], pos + j,
                                       kc_d, vc_d)
                d_cur = jnp.argmax(
                    logits_d(pd, xd[:, -1]).astype(jnp.float32),
                    -1).astype(jnp.int32)                  # [1]
                props.append(d_cur)
            # write p_{k-1}'s KV too (logits discarded): when all k
            # proposals are accepted the next round starts PAST this
            # column, and an unwritten (zero) column inside the accepted
            # prefix would poison every later draft query's attention
            _, kc_d, vc_d = fwd_d(pd, d_cur[:, None], pos + k, kc_d, vc_d)
            props_a = jnp.stack(props, axis=1)             # [1, k]
            # --- target verifies in ONE (k+1)-token forward --------------
            seq = jnp.concatenate([cur[:, None], props_a], axis=1)
            xt, kc_t, vc_t = fwd_t(pt, seq, pos, kc_t, vc_t)
            preds = jnp.argmax(
                logits_t(pt, xt).astype(jnp.float32),
                -1).astype(jnp.int32)                      # [1, k+1]
            # longest accepted prefix: p_j must equal the target's argmax
            # after the same prefix (preds[:, j])
            matches = (props_a == preds[:, :k]).astype(jnp.int32)
            m = jnp.cumprod(matches, axis=1).sum(axis=1)[0]  # scalar 0..k
            # emitted this round: p_0..p_{m-1} then the target fix-up
            # preds[m]; tail slots are junk overwritten by later rounds
            j_idx = jnp.arange(k + 1)
            fixup = preds[0, m]
            emit = jnp.where(j_idx < m, jnp.pad(props_a[0], (0, 1)),
                             fixup)                        # [k+1]
            if eos >= 0:
                # dense-generate parity: everything after the first eos in
                # the ACCEPTED slice becomes eos, and the loop stops
                seen = jnp.cumsum((emit == eos) & (j_idx <= m)) > 0
                emit = jnp.where(seen, eos, emit)
                done = done | seen[m]
            out_buf = jax.lax.dynamic_update_slice(out_buf, emit[None],
                                                   (0, emitted))
            return (pos + m + 1, preds[:, m], emitted + m + 1, out_buf,
                    kc_t, vc_t, kc_d, vc_d, rounds + 1, done)

        def cond(carry):
            return (carry[2] < max_new_tokens) & ~carry[-1]

        init = (jnp.int32(s0 - 1), cur, jnp.int32(0), out_buf,
                kc_t, vc_t, kc_d, vc_d, jnp.int32(0),
                jnp.asarray(False))
        (pos, cur, emitted, out_buf, *_, rounds, done) = jax.lax.while_loop(
            cond, round_body, init)
        out = out_buf[:, :max_new_tokens]
        if eos >= 0:
            # early stop leaves the tail unwritten: fill with eos (what the
            # dense scan would have emitted after done)
            out = jnp.where(jnp.arange(max_new_tokens)[None] >= emitted,
                            eos, out)
        return out, rounds

    cache_key = ("spec", b, s0, max_new_tokens, k, untied, untied_bias,
                 d_untied, d_untied_bias, str(compute_dtype), cache_dtype,
                 # value-based draft identity (id() could alias a GC'd
                 # model of a different architecture)
                 d_cfg.num_layers, d_cfg.hidden_size, d_cfg.num_heads,
                 getattr(d_cfg, "num_kv_heads", d_cfg.num_heads),
                 d_cfg.vocab_size, d_cfg.max_seq_len,
                 # the jitted closure also bakes the draft's attention
                 # window and gelu flavor — a second draft sharing the
                 # dims but differing here must NOT reuse the program
                 getattr(d_cfg, "attention_window", None),
                 getattr(d_cfg, "gelu_approx", False), eos_token_id,
                 ("tp", tp_mesh) if tp_mesh is not None else None)
    store = model.__dict__.setdefault("_generate_compiled", {})
    if cache_key not in store:
        if tp_mesh is None:
            store[cache_key] = jax.jit(run)
        else:
            from jax.sharding import PartitionSpec as P

            # run(pt, pd, ids): a bare P() prefix replicates the whole
            # draft-param dict and the ids
            store[cache_key] = _tp_wrap(run, tp_mesh, tp_specs, 2,
                                        (P(), P()))
    out, rounds = store[cache_key](params, params_d, ids)
    full = jnp.concatenate([ids.astype(out.dtype), out], axis=1)
    return Tensor(full), int(rounds)


def _ragged_setup(mask_, b, s0, T):
    """Shared ragged-batch derivation for both decode programs: per-row real
    lengths, the [b, T] key-validity mask (generated columns always valid)
    and the prefill position ids for LEFT-padded prompts."""
    import jax.numpy as jnp

    if mask_ is None:
        return None, None, None
    lens = jnp.sum(mask_, axis=1).astype(jnp.int32)
    key_valid = jnp.concatenate(
        [mask_.astype(bool), jnp.ones((b, T - s0), bool)], axis=1)
    pos_ids = jnp.maximum(jnp.arange(s0)[None, :] - (s0 - lens)[:, None], 0)
    return lens, key_valid, pos_ids


def _left_pad_mask(attention_mask, b, s0):
    """Validate/convert a [b, s0] keep-mask for ragged decode. Rows must be
    LEFT-padded (zeros then ones) so the last column is every row's final
    real token — the position the next-token logits read."""
    if attention_mask is None:
        return None
    import jax.numpy as jnp

    m = attention_mask._data if isinstance(attention_mask, Tensor) else \
        jnp.asarray(np.asarray(attention_mask))
    if m.shape != (b, s0):
        raise ValueError(f"attention_mask shape {tuple(m.shape)} != "
                         f"{(b, s0)}")
    mi = m.astype(jnp.int32)
    host = np.asarray(mi)  # generate() is a host API: masks arrive concrete
    if not np.isin(host, (0, 1)).all():
        raise ValueError("attention_mask must be binary (0 = pad, 1 = "
                         "attend); got other values")
    if not (np.diff(host, axis=1) >= 0).all():
        raise ValueError(
            "attention_mask must be LEFT-padded (0s then 1s per row): "
            "right-padded prompts would put pad tokens at the positions "
            "the decode reads — re-pad with the prompt at the END")
    if not host.any(axis=1).all():
        raise ValueError("attention_mask has an all-pad row")
    return mi


def _gpt_beam_search(model, input_ids, max_new_tokens, num_beams,
                     eos_token_id, length_penalty, dtype=None,
                     attention_mask=None, cache_dtype=None, tp_mesh=None):
    """Beam search over the same fused KV-cache program: prefill once at
    batch b, tile the cache per beam ([L, b*K, H, T, hd]), and lax.scan
    steps that (a) add log-probs, (b) take the joint top-K over K*V
    continuations, (c) reorder the cache by surviving parent beam, and
    (d) record (token, parent) for the reverse-scan backtrace. Finished
    beams (eos) only continue with eos at zero added log-prob. Scores are
    length-normalized by (new_len ** length_penalty) at the final pick."""
    import jax
    import jax.numpy as jnp

    cfg, ids, b, s0, T, untied, untied_bias, params = _decode_setup(
        model, input_ids, max_new_tokens)
    if num_beams < 2:
        raise ValueError("num_beams must be >= 2 for beam search")
    if num_beams > cfg.vocab_size:
        raise ValueError(f"num_beams ({num_beams}) cannot exceed "
                         f"vocab_size ({cfg.vocab_size})")
    L, Hh = cfg.num_layers, cfg.num_heads
    hd = cfg.hidden_size // Hh
    K, V = num_beams, cfg.vocab_size
    tp_axis, tp_size, tp_specs = None, 1, None
    if tp_mesh is not None:
        tp_axis, tp_size, params, tp_specs = _tp_setup(tp_mesh, cfg, params)
    fwd, logits_of, cache_init = _decode_fns(cfg, untied, untied_bias,
                                             cache_dtype=cache_dtype,
                                             tp_axis=tp_axis,
                                             tp_size=tp_size)
    eos = -1 if eos_token_id is None else int(eos_token_id)
    compute_dtype = _decode_compute_dtype(dtype)
    mask = _left_pad_mask(attention_mask, b, s0)

    def run(p, ids_, mask_):
        if compute_dtype is not None:
            # bf16 cache matters MOST here: the cache is K x larger
            p = {k: (v.astype(compute_dtype)
                     if jnp.issubdtype(v.dtype, jnp.floating) else v)
                 for k, v in p.items()}
        kc, vc = cache_init(b, T, compute_dtype or jnp.float32)
        lens, key_valid, pos_ids = _ragged_setup(mask_, b, s0, T)
        x, kc, vc = fwd(p, ids_, 0, kc, vc, key_valid=key_valid,
                        pos_ids=pos_ids)
        logp0 = jax.nn.log_softmax(
            logits_of(p, x[:, -1]).astype(jnp.float32), -1)      # [b, V]
        scores, tok = jax.lax.top_k(logp0, K)                    # [b, K]
        tok = tok.astype(jnp.int32)
        done = tok == eos
        # tile cache per beam: batch-major layout [b*K] = (b0k0, b0k1, ...)
        kc = _cache_map(lambda a: jnp.repeat(a, K, axis=1), kc)
        vc = _cache_map(lambda a: jnp.repeat(a, K, axis=1), vc)
        kv_beam = None if key_valid is None else \
            jnp.repeat(key_valid, K, axis=0)                     # [b*K, T]
        lens_beam = None if lens is None else jnp.repeat(lens, K)
        batch_base = (jnp.arange(b) * K)[:, None]                # [b, 1]

        gen_len = jnp.ones_like(scores)  # per-beam generated length

        def step(carry, i):
            tok, scores, done, gen_len, kc, vc = carry
            step_pos = None if lens_beam is None else \
                (lens_beam + (i - 1))[:, None]
            x, kc, vc = fwd(p, tok.reshape(b * K, 1), s0 + i - 1, kc, vc,
                            key_valid=kv_beam, pos_ids=step_pos)
            logp = jax.nn.log_softmax(
                logits_of(p, x[:, 0]).astype(jnp.float32),
                -1).reshape(b, K, V)
            # finished beams: only eos continues, at no cost
            if eos >= 0:
                frozen = jnp.full((V,), -jnp.inf).at[eos].set(0.0)
                logp = jnp.where(done[:, :, None], frozen[None, None], logp)
            total = scores[:, :, None] + logp                    # [b, K, V]
            scores, sel = jax.lax.top_k(total.reshape(b, K * V), K)
            parent = (sel // V).astype(jnp.int32)                # [b, K]
            tok = (sel % V).astype(jnp.int32)
            parent_done = jnp.take_along_axis(done, parent, axis=1)
            # a beam that was already finished keeps its length; live ones
            # grow to i+1 tokens (GNMT length normalization needs this)
            gen_len = jnp.where(parent_done,
                                jnp.take_along_axis(gen_len, parent, axis=1),
                                i + 1.0) \
                if eos >= 0 else gen_len + 1.0
            done = parent_done | (tok == eos) \
                if eos >= 0 else jnp.zeros_like(tok, bool)
            # reorder beam-expanded cache rows by surviving parent
            rows = (batch_base + parent).reshape(-1)             # [b*K]
            kc = _cache_map(lambda a: a[:, rows], kc)
            vc = _cache_map(lambda a: a[:, rows], vc)
            return (tok, scores, done, gen_len, kc, vc), (tok, parent)

        init_tok, init_scores, init_done = tok, scores, done
        if max_new_tokens == 1:
            best = jnp.argmax(init_scores, -1)
            return jnp.take_along_axis(init_tok, best[:, None], 1), \
                jnp.take_along_axis(init_scores, best[:, None], 1)[:, 0]
        (tok, scores, done, gen_len, _, _), (toks, parents) = jax.lax.scan(
            step, (init_tok, init_scores, init_done, gen_len, kc, vc),
            jnp.arange(1, max_new_tokens))
        # GNMT-style final pick: each beam normalized by ITS generated
        # length (eos-frozen beams keep their shorter length)
        norm = scores / (gen_len ** length_penalty)
        best = jnp.argmax(norm, -1)                              # [b]
        final_score = jnp.take_along_axis(scores, best[:, None], 1)[:, 0]

        # backtrace: walk parents from the last step down to the prefill pick
        def back(beam, t):
            tk = jnp.take_along_axis(toks[t], beam[:, None], 1)[:, 0]
            beam = jnp.take_along_axis(parents[t], beam[:, None], 1)[:, 0]
            return beam, tk

        beam, rev = jax.lax.scan(back, best,
                                 jnp.arange(max_new_tokens - 2, -1, -1))
        first = jnp.take_along_axis(init_tok, beam[:, None], 1)  # [b, 1]
        seq = jnp.concatenate([first, rev.T[:, ::-1]], axis=1)
        return seq, final_score

    cache_key = ("beam", b, s0, max_new_tokens, K, eos, untied, untied_bias,
                 float(length_penalty), str(compute_dtype), mask is not None,
                 cache_dtype,
                 ("tp", tp_mesh) if tp_mesh is not None else None)
    store = model.__dict__.setdefault("_generate_compiled", {})
    if cache_key not in store:
        if tp_mesh is None:
            store[cache_key] = jax.jit(run)
        else:
            from jax.sharding import PartitionSpec as P

            store[cache_key] = _tp_wrap(run, tp_mesh, tp_specs, 2,
                                        (P(), P()))
    out, score = store[cache_key](params, ids, mask)
    full = jnp.concatenate([ids.astype(out.dtype), out], axis=1)
    return Tensor(full), Tensor(score)


# ---------------------------------------------------------------------------
# Pipeline-parallel decomposition (distributed.pipeline.PipelineTrainer model
# protocol: pre / homogeneous stages / post+loss).
# ---------------------------------------------------------------------------

class GPTEmbed(nn.Layer):
    """First pipeline section: token + position embedding (shares the parent
    model's wte/wpe parameter tensors)."""

    def __init__(self, wte, wpe, dropout):
        super().__init__()
        self.wte = wte
        self.wpe = wpe
        self.drop = nn.Dropout(dropout)

    def forward(self, input_ids):
        from ..tensor.creation import arange

        s = input_ids.shape[-1]
        pos = arange(s, dtype="int32")  # int32: x64 is off on TPU/CPU — an "int64" request
        # is truncated with a per-call UserWarning (caught by the analysis trace-warnings gate)
        return self.drop(self.wte(input_ids) + self.wpe(pos))


class GPTStage(nn.Layer):
    """One pipeline stage: a run of consecutive GPTBlocks (shares the parent's
    block sublayers, so parameters stay the same Tensor objects)."""

    def __init__(self, blocks):
        super().__init__()
        self.blocks = nn.LayerList(blocks)

    def forward(self, x):
        for blk in self.blocks:
            x = blk(x)
        return x


class GPTHeadLoss(nn.Layer):
    """Last pipeline section: final LayerNorm + LM head + cross-entropy.

    The head is UNTIED here (initialized from a copy of wte): pipeline splits
    put the embedding on stage 0 and the head on the last stage — the megatron/
    reference convention where tied weights need an extra embedding grad
    all-reduce between first and last stage; we untie instead and document it.
    """

    def __init__(self, ln_f, wte_weight):
        super().__init__()
        self.ln_f = ln_f
        v, h = wte_weight.shape
        self.head = nn.Linear(h, v, bias_attr=False)
        self.head.weight._data = wte_weight._data.T.copy()

    def forward(self, h, labels):
        h = self.ln_f(h)
        logits = self.head(h)
        b, s, v = logits.shape
        return F.cross_entropy(logits.reshape([b * s, v]), labels.reshape([b * s]))


def _gpt_pipeline_split(model, pp_degree):
    """Split a GPTForCausalLM into (pre, stages, post_loss) for PipelineTrainer.

    Stage layers share the model's block parameter tensors; each stage gets
    num_layers // pp_degree consecutive blocks (must divide evenly so stages
    are structurally identical — the stacked-params representation needs it).
    """
    cfg = model.cfg
    if cfg.num_layers % pp_degree != 0:
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by "
                         f"pp_degree={pp_degree}")
    per = cfg.num_layers // pp_degree
    gpt = model.gpt
    pre = GPTEmbed(gpt.wte, gpt.wpe, cfg.dropout)
    stages = [GPTStage(list(gpt.blocks)[i * per:(i + 1) * per])
              for i in range(pp_degree)]
    post = GPTHeadLoss(gpt.ln_f, gpt.wte.weight)
    # expose the untied head on the model so its forward path and state_dict
    # reflect pipelined training after sync_to_layer
    model.lm_head = post.head
    return pre, stages, post


def gpt2_small(**kw):
    return GPTForCausalLM(GPTConfig.small())


def gpt2_medium(**kw):
    return GPTForCausalLM(GPTConfig.medium())


class GPTDecodeModel(_decode_model.DecodeModel):
    """The gpt family's DecodeModel adapter (serving/decode_model.py):
    the serving tier's ONLY doorway into this module — every method
    delegates to the same decode helpers generate()/ServingEngine
    historically used, so engine outputs through the registry are
    byte-identical to the direct-import era."""

    name = "gpt"

    def check_config(self, cfg):
        _check_decode_config(cfg)

    def compute_dtype(self, dtype):
        return _decode_compute_dtype(dtype)

    def extract_params(self, model, who):
        untied, untied_bias, params = _decode_params(model, who)
        return params, (untied, untied_bias)

    def decode_fns(self, cfg, aux, cache_dtype=None, tp_axis=None,
                   tp_size=1):
        untied, untied_bias = aux
        return _decode_fns(cfg, untied, untied_bias,
                           cache_dtype=cache_dtype, tp_axis=tp_axis,
                           tp_size=tp_size)

    def tp_setup(self, tp_mesh, cfg, params):
        return _tp_setup(tp_mesh, cfg, params)

    def tp_wrap(self, run, tp_mesh, tp_specs, n_extra_in, out_specs,
                in_specs=None, donate=()):
        return _tp_wrap(run, tp_mesh, tp_specs, n_extra_in, out_specs,
                        in_specs=in_specs, donate=donate)

    def cache_spec(self, cfg):
        KVh = getattr(cfg, "num_kv_heads", None) or cfg.num_heads
        hd = cfg.hidden_size // cfg.num_heads
        return {"kind": "kv_pair",
                "layout": "[L, B, KVh, T, hd]",
                "axes": {"L": cfg.num_layers, "KVh": KVh,
                         "T": cfg.max_seq_len, "hd": hd},
                "quantized": "per-side (values, scales) tuple when the "
                             "engine's cache_dtype is int8/fp8"}

    # multi-LoRA batched decode: the four adapter sites mirror block()'s
    # four matmuls. Every slot carries all four kinds (absent sites are
    # exact zeros) so hot-loading an adapter into a freed slot is one
    # uniform .at[slot].set — no per-site-set program variants.
    _LORA_SITES = {"attn.qkv": "qkv", "attn.proj": "proj",
                   "mlp.fc1": "fc1", "mlp.fc2": "fc2"}

    def _lora_dims(self, cfg):
        Hh = cfg.num_heads
        KVh = getattr(cfg, "num_kv_heads", None) or Hh
        hd = cfg.hidden_size // Hh
        h, inner = cfg.hidden_size, cfg.intermediate_size
        return {"qkv": (h, (Hh + 2 * KVh) * hd), "proj": (h, h),
                "fc1": (h, inner), "fc2": (inner, h)}

    def lora_init(self, cfg, n_slots, rank, dtype=None):
        import jax.numpy as jnp

        dt = dtype or jnp.float32
        L = cfg.num_layers
        pack = {"scale": jnp.zeros((n_slots,), jnp.float32)}
        for kind, (din, dout) in self._lora_dims(cfg).items():
            pack[kind] = {
                "A": jnp.zeros((n_slots, L, din, rank), dt),
                "B": jnp.zeros((n_slots, L, rank, dout), dt)}
        return pack

    def lora_pack(self, cfg, exported, rank):
        L = cfg.num_layers
        r = int(exported["rank"])
        if r > rank:
            raise ValueError(
                f"adapter rank {r} exceeds the engine's lora_rank={rank}; "
                "rebuild the engine with a larger lora_rank")
        dims = self._lora_dims(cfg)
        slot = {"scale": float(exported["scaling"])}
        for kind, (din, dout) in dims.items():
            slot[kind] = {"A": np.zeros((L, din, rank), np.float32),
                          "B": np.zeros((L, rank, dout), np.float32)}
        pat = re.compile(r"(?:^|\.)blocks\.(\d+)\.(attn\.qkv|attn\.proj|"
                         r"mlp\.fc1|mlp\.fc2)$")
        for qual, fac in exported["factors"].items():
            m = pat.search(qual)
            if m is None:
                raise ValueError(
                    f"adapter site {qual!r} has no batched-decode "
                    "injection point (gpt serves LoRA on attn.qkv/"
                    "attn.proj/mlp.fc1/mlp.fc2 only) — merge_lora this "
                    "adapter and serve it dense instead")
            i, kind = int(m.group(1)), self._LORA_SITES[m.group(2)]
            A, B = np.asarray(fac["A"]), np.asarray(fac["B"])
            din, dout = dims[kind]
            if A.shape != (din, r) or B.shape != (r, dout):
                raise ValueError(
                    f"adapter site {qual!r}: factors {A.shape}/{B.shape} "
                    f"do not match the config ({(din, r)}/{(r, dout)})")
            if not 0 <= i < L:
                raise ValueError(f"adapter site {qual!r}: layer {i} out of "
                                 f"range for num_layers={L}")
            slot[kind]["A"][i, :, :r] = A
            slot[kind]["B"][i, :r, :] = B
        return slot

    def matches(self, model):
        return isinstance(model, GPTForCausalLM)


_decode_model.register_decode_model(GPTDecodeModel())
