"""Model zoo: flagship LMs (GPT/BERT) + vision models re-export."""
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertForQuestionAnswering,
    BertForSequenceClassification,
    BertForTokenClassification,
    BertModel,
    BertPretrainLoss,
    bert_base,
)
from .ernie import (  # noqa: F401
    ErnieConfig,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieModel,
    ErniePretrainLoss,
    ernie_base,
    knowledge_mask,
)
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainLoss,
    gpt2_medium,
    gpt2_small,
)
from .hf_bridge import (  # noqa: F401
    bert_from_huggingface,
    ernie_from_huggingface,
    gpt2_from_huggingface,
    gpt2_to_huggingface,
)
