"""Model zoo: flagship LMs (GPT/BERT) + vision models re-export."""
from .bert import BertConfig, BertForPretraining, BertModel, BertPretrainLoss, bert_base  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainLoss,
    gpt2_medium,
    gpt2_small,
)
