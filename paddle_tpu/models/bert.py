"""BERT/ERNIE-base encoder for MLM pretraining (BASELINE.json config #3: BERT-base
fleet data-parallel pretraining — the north-star benchmark model).

Built on paddle_tpu.nn.TransformerEncoder (layer/transformer.py parity surface)."""
import numpy as np

from .. import nn
from ..nn import functional as F


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
                 intermediate_size=3072, max_position=512, type_vocab_size=2,
                 dropout=0.1, layer_norm_eps=1e-12):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps  # BERT convention (HF parity)

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                          intermediate_size=128, max_position=128, dropout=0.0)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.word = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.token_type = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size)
        self.ln = nn.LayerNorm(cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None):
        from ..tensor.creation import arange, zeros_like

        s = input_ids.shape[1]
        pos = arange(s, dtype="int32")  # int32: x64 is off on TPU/CPU — an "int64" request
        # is truncated with a per-call UserWarning (caught by the analysis trace-warnings gate)
        x = self.word(input_ids) + self.position(pos)
        if token_type_ids is None:
            # BERT semantics: absent segment ids mean segment 0, whose
            # embedding still contributes (trained checkpoints rely on it)
            token_type_ids = zeros_like(input_ids)
        x = x + self.token_type(token_type_ids)
        return self.drop(self.ln(x))


class BertModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation="gelu",
        )
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        # thread the config's LayerNorm epsilon through every norm (the
        # encoder-layer API has no eps knob; rebuilt models keep parity
        # because the eps rides BertConfig, not a post-hoc patch)
        eps = getattr(cfg, "layer_norm_eps", 1e-12)
        for _, sub in self.named_sublayers(include_self=True):
            if isinstance(sub, nn.LayerNorm):
                sub._epsilon = eps

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        # [b, s] keep-masks normalize inside the shared attention stack
        # (nn/layer/transformer.py _convert_attn_mask)
        x = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM head (tied) + NSP head."""

    def __init__(self, cfg):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.ln = nn.LayerNorm(cfg.hidden_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)
        self.cfg = cfg

    def forward(self, input_ids, token_type_ids=None):
        seq, pooled = self.bert(input_ids, token_type_ids)
        h = self.ln(F.gelu(self.transform(seq)))
        from ..tensor.math import matmul

        mlm_logits = matmul(h, self.bert.embeddings.word.weight, transpose_y=True)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertForSequenceClassification(nn.Layer):
    """Pooled-[CLS] classification head (GLUE-style fine-tuning)."""

    def __init__(self, cfg, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForTokenClassification(nn.Layer):
    """Per-token tagging head (NER-style fine-tuning)."""

    def __init__(self, cfg, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(seq))


class BertForQuestionAnswering(nn.Layer):
    """SQuAD-style span head: (start_logits, end_logits)."""

    def __init__(self, cfg):
        super().__init__()
        self.bert = BertModel(cfg)
        self.qa_outputs = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.qa_outputs(seq)          # [b, s, 2]
        return logits[:, :, 0], logits[:, :, 1]


class BertPretrainLoss(nn.Layer):
    def forward(self, outputs, labels):
        mlm_logits, _ = outputs if isinstance(outputs, (tuple, list)) else (outputs, None)
        b, s, v = mlm_logits.shape
        return F.cross_entropy(
            mlm_logits.reshape([b * s, v]), labels.reshape([b * s]), ignore_index=-100
        )


def bert_base(**kw):
    return BertModel(BertConfig.base())
