"""HuggingFace transformers interop: convert GPT2LMHeadModel /
BertModel checkpoints into this framework's models (the migration path for
users with existing torch weights).

Layout notes (verified against transformers' GPT2 state_dict):
 * HF Conv1D stores weights [in, out] — identical to this framework's
   Linear, so qkv/proj/fc weights copy without transpose.
 * c_attn packs [Q|K|V] along the output dim in that order, matching
   GPTAttention's reshape([b, s, 3, H, hd]).
 * HF GPT-2 uses the tanh-approximate gelu ("gelu_new"): the converted
   config sets gelu_approx=True so logits match bit-for-tolerance
   (tests/test_hf_bridge.py pins parity against the torch forward).
"""
import numpy as np

from .gpt import GPTConfig, GPTForCausalLM


# per-block weight-name pairs shared by BOTH GPT-2 bridge directions
# (ours suffix, HF suffix) — HF Conv1D's [in, out] matches our Linear
_GPT2_LAYER_MAP = [
    ("ln1.weight", "ln_1.weight"), ("ln1.bias", "ln_1.bias"),
    ("attn.qkv.weight", "attn.c_attn.weight"),
    ("attn.qkv.bias", "attn.c_attn.bias"),
    ("attn.proj.weight", "attn.c_proj.weight"),
    ("attn.proj.bias", "attn.c_proj.bias"),
    ("ln2.weight", "ln_2.weight"), ("ln2.bias", "ln_2.bias"),
    ("mlp.fc1.weight", "mlp.c_fc.weight"),
    ("mlp.fc1.bias", "mlp.c_fc.bias"),
    ("mlp.fc2.weight", "mlp.c_proj.weight"),
    ("mlp.fc2.bias", "mlp.c_proj.bias"),
]


def _put(ours, name, arr, transpose=False):
    """Copy one weight into the converted model, guarding layout: a shape
    mismatch here is exactly what a transpose/packing regression produces."""
    t = ours[name]
    if transpose:
        arr = arr.T
    if tuple(t.shape) != tuple(arr.shape):
        raise ValueError(f"{name}: shape {tuple(arr.shape)} != "
                         f"{tuple(t.shape)}")
    t.set_value(np.ascontiguousarray(arr))


def gpt2_from_huggingface(hf_model=None, model_name=None, dtype="float32"):
    """Build a GPTForCausalLM carrying the weights of a transformers
    GPT2LMHeadModel.

    Pass an instantiated `hf_model` (offline-safe), or `model_name` to let
    transformers resolve it (requires the checkpoint in the local HF cache —
    this image has no network egress)."""
    if hf_model is None:
        if model_name is None:
            raise ValueError("pass hf_model= or model_name=")
        from transformers import GPT2LMHeadModel

        hf_model = GPT2LMHeadModel.from_pretrained(model_name)
    hc = hf_model.config
    act = getattr(hc, "activation_function", "gelu_new")
    if act in ("gelu_new", "gelu_pytorch_tanh"):
        gelu_approx = True
    elif act == "gelu":
        gelu_approx = False
    else:
        raise ValueError(f"unsupported activation_function {act!r}; this "
                         "bridge maps gelu_new/gelu_pytorch_tanh/gelu only")
    cfg = GPTConfig(vocab_size=hc.vocab_size, hidden_size=hc.n_embd,
                    num_layers=hc.n_layer, num_heads=hc.n_head,
                    max_seq_len=hc.n_positions,
                    intermediate_size=getattr(hc, "n_inner", None)
                    or 4 * hc.n_embd,
                    dropout=0.0, gelu_approx=gelu_approx)
    model = GPTForCausalLM(cfg)

    sd = {k: v.detach().cpu().numpy().astype(dtype)
          for k, v in hf_model.state_dict().items()}
    ours = dict(model.named_parameters())

    def put(name, arr):
        _put(ours, name, arr)

    put("gpt.wte.weight", sd["transformer.wte.weight"])
    put("gpt.wpe.weight", sd["transformer.wpe.weight"])
    for i in range(cfg.num_layers):
        hf = f"transformer.h.{i}."
        us = f"gpt.blocks.{i}."
        for mine, theirs in _GPT2_LAYER_MAP:
            put(us + mine, sd[hf + theirs])
    put("gpt.ln_f.weight", sd["transformer.ln_f.weight"])
    put("gpt.ln_f.bias", sd["transformer.ln_f.bias"])
    # lm_head ties to wte in HF GPT-2 exactly like this framework's tied head
    model.eval()
    return model


def _bert_family_sd(hf_model, prefix, dtype):
    """state_dict -> numpy with the wrapper prefix stripped and the pooler
    presence guarded (shared by the BERT and ERNIE bridges)."""
    sd = {k: v.detach().cpu().numpy().astype(dtype)
          for k, v in hf_model.state_dict().items()}
    if any(k.startswith(prefix) for k in sd):
        sd = {k[len(prefix):]: v for k, v in sd.items()
              if k.startswith(prefix)}
    if "pooler.dense.weight" not in sd:
        raise ValueError(
            "checkpoint has no pooler (e.g. a bare MLM head / "
            "add_pooling_layer=False); convert the base model with a pooler")
    return sd


def _map_bert_embeddings_and_pooler(put, sd):
    """Shared word/position/token-type/LN embedding + pooler mapping."""
    put("embeddings.word.weight", sd["embeddings.word_embeddings.weight"])
    put("embeddings.position.weight",
        sd["embeddings.position_embeddings.weight"])
    put("embeddings.token_type.weight",
        sd["embeddings.token_type_embeddings.weight"])
    put("embeddings.ln.weight", sd["embeddings.LayerNorm.weight"])
    put("embeddings.ln.bias", sd["embeddings.LayerNorm.bias"])
    put("pooler.weight", sd["pooler.dense.weight"], transpose=True)
    put("pooler.bias", sd["pooler.dense.bias"])


def _map_bert_encoder(put, sd, num_layers):
    """Shared BERT-family encoder mapping (torch [out,in] Linears transpose
    into our [in,out]; post-LN layout) — used by the BERT and ERNIE bridges."""
    for i in range(num_layers):
        hf = f"encoder.layer.{i}."
        us = f"encoder.layers.{i}."
        for mine, theirs in (("q_proj", "attention.self.query"),
                             ("k_proj", "attention.self.key"),
                             ("v_proj", "attention.self.value"),
                             ("out_proj", "attention.output.dense")):
            put(us + f"self_attn.{mine}.weight",
                sd[hf + theirs + ".weight"], transpose=True)
            put(us + f"self_attn.{mine}.bias", sd[hf + theirs + ".bias"])
        put(us + "norm1.weight", sd[hf + "attention.output.LayerNorm.weight"])
        put(us + "norm1.bias", sd[hf + "attention.output.LayerNorm.bias"])
        put(us + "linear1.weight", sd[hf + "intermediate.dense.weight"],
            transpose=True)
        put(us + "linear1.bias", sd[hf + "intermediate.dense.bias"])
        put(us + "linear2.weight", sd[hf + "output.dense.weight"],
            transpose=True)
        put(us + "linear2.bias", sd[hf + "output.dense.bias"])
        put(us + "norm2.weight", sd[hf + "output.LayerNorm.weight"])
        put(us + "norm2.bias", sd[hf + "output.LayerNorm.bias"])


def bert_from_huggingface(hf_model=None, model_name=None, dtype="float32"):
    """Build this framework's BertModel carrying a transformers BertModel's
    weights. torch Linear stores [out, in] — transposed into this
    framework's [in, out] convention; embeddings/LayerNorms copy directly.
    Post-LN encoder layers match BERT's architecture one-to-one
    (tests/test_hf_bridge.py pins hidden-state + pooler parity)."""
    if hf_model is None:
        if model_name is None:
            raise ValueError("pass hf_model= or model_name=")
        from transformers import BertModel as HFBert

        hf_model = HFBert.from_pretrained(model_name)
    hc = hf_model.config
    if getattr(hc, "hidden_act", "gelu") != "gelu":
        raise ValueError(f"unsupported hidden_act {hc.hidden_act!r}; this "
                         "bridge maps BERT's standard gelu only")
    pet = getattr(hc, "position_embedding_type", "absolute")
    if pet != "absolute":
        raise ValueError(f"unsupported position_embedding_type {pet!r}; "
                         "relative-position checkpoints carry "
                         "distance_embedding weights this bridge does not "
                         "map — converting would silently diverge")
    from .bert import BertConfig, BertModel

    cfg = BertConfig(vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
                     num_layers=hc.num_hidden_layers,
                     num_heads=hc.num_attention_heads,
                     intermediate_size=hc.intermediate_size,
                     max_position=hc.max_position_embeddings,
                     type_vocab_size=hc.type_vocab_size, dropout=0.0,
                     layer_norm_eps=float(
                         getattr(hc, "layer_norm_eps", 1e-12)))
    model = BertModel(cfg)
    sd = _bert_family_sd(hf_model, "bert.", dtype)
    ours = dict(model.named_parameters())

    def put(name, arr, transpose=False):
        _put(ours, name, arr, transpose=transpose)

    _map_bert_embeddings_and_pooler(put, sd)
    _map_bert_encoder(put, sd, cfg.num_layers)
    model.eval()
    return model


def gpt2_to_huggingface(model, hf_model=None):
    """Export a GPTForCausalLM's weights INTO a transformers GPT2LMHeadModel
    (the reverse bridge — take trained models back to the torch ecosystem).
    Pass an instantiated hf_model with a matching config, or one is built
    from the model's GPTConfig. Returns the hf_model."""
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = model.cfg
    if getattr(model, "lm_head", None) is not None:
        raise ValueError("untied-head models (after pipeline_split) do not "
                         "map onto HF GPT-2's tied head; export the tied "
                         "pre-split model")
    if cfg.num_experts > 0:
        raise ValueError("MoE models have no GPT-2 equivalent (expert MLPs "
                         "replace dense fc1/fc2); export is unsupported")
    if hf_model is not None:
        act = getattr(hf_model.config, "activation_function", "gelu_new")
        want_approx = act in ("gelu_new", "gelu_pytorch_tanh")
        if act not in ("gelu_new", "gelu_pytorch_tanh", "gelu") or \
                want_approx != bool(cfg.gelu_approx):
            raise ValueError(
                f"hf_model activation_function {act!r} does not match "
                f"gelu_approx={cfg.gelu_approx}; logits would silently "
                "diverge")
    if hf_model is None:
        hf_model = GPT2LMHeadModel(GPT2Config(
            vocab_size=cfg.vocab_size, n_positions=cfg.max_seq_len,
            n_embd=cfg.hidden_size, n_layer=cfg.num_layers,
            n_head=cfg.num_heads,
            n_inner=cfg.intermediate_size,
            activation_function=("gelu_new" if cfg.gelu_approx else "gelu"),
            resid_pdrop=cfg.dropout, embd_pdrop=cfg.dropout,
            attn_pdrop=cfg.dropout))
    ours = {n: np.asarray(p._data) for n, p in model.named_parameters()}
    sd = {}
    sd["transformer.wte.weight"] = ours["gpt.wte.weight"]
    sd["transformer.wpe.weight"] = ours["gpt.wpe.weight"]
    for i in range(cfg.num_layers):
        hf = f"transformer.h.{i}."
        us = f"gpt.blocks.{i}."
        for mine, theirs in _GPT2_LAYER_MAP:
            sd[hf + theirs] = ours[us + mine]
    sd["transformer.ln_f.weight"] = ours["gpt.ln_f.weight"]
    sd["transformer.ln_f.bias"] = ours["gpt.ln_f.bias"]
    sd["lm_head.weight"] = ours["gpt.wte.weight"]  # tied
    tensors = {k: torch.tensor(np.ascontiguousarray(v))
               for k, v in sd.items()}
    missing, unexpected = hf_model.load_state_dict(tensors, strict=False)
    # attn.bias (causal mask buffers) are derived, not weights; anything
    # else missing means a layout/config mismatch
    real_missing = [k for k in missing
                    if not k.endswith((".attn.bias", ".attn.masked_bias"))]
    if real_missing or unexpected:
        raise ValueError(f"export mismatch — missing: {real_missing}, "
                         f"unexpected: {unexpected}")
    hf_model.eval()
    return hf_model


def ernie_from_huggingface(hf_model=None, model_name=None, dtype="float32"):
    """Build this framework's ErnieModel from a transformers ErnieModel
    (the PaddleNLP-lineage ERNIE port in transformers): same BERT-family
    encoder mapping plus the optional task-type embedding table
    (tests/test_hf_bridge.py pins hidden+pooler parity)."""
    if hf_model is None:
        if model_name is None:
            raise ValueError("pass hf_model= or model_name=")
        from transformers import ErnieModel as HFErnie

        hf_model = HFErnie.from_pretrained(model_name)
    hc = hf_model.config
    if getattr(hc, "hidden_act", "gelu") not in ("gelu", "relu"):
        raise ValueError(f"unsupported hidden_act {hc.hidden_act!r}")
    pet = getattr(hc, "position_embedding_type", "absolute")
    if pet != "absolute":
        raise ValueError(f"unsupported position_embedding_type {pet!r}")
    from .ernie import ErnieConfig, ErnieModel

    use_task = bool(getattr(hc, "use_task_id", False))
    cfg = ErnieConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        num_layers=hc.num_hidden_layers, num_heads=hc.num_attention_heads,
        intermediate_size=hc.intermediate_size,
        max_position=hc.max_position_embeddings,
        type_vocab_size=hc.type_vocab_size,
        task_type_vocab_size=(getattr(hc, "task_type_vocab_size", 0)
                              if use_task else 0),
        dropout=0.0, activation=hc.hidden_act,
        layer_norm_eps=float(getattr(hc, "layer_norm_eps", 1e-12)))
    model = ErnieModel(cfg)
    sd = _bert_family_sd(hf_model, "ernie.", dtype)
    ours = dict(model.named_parameters())

    def put(name, arr, transpose=False):
        _put(ours, name, arr, transpose=transpose)

    _map_bert_embeddings_and_pooler(put, sd)
    if use_task:
        put("embeddings.task_type.weight",
            sd["embeddings.task_type_embeddings.weight"])
    _map_bert_encoder(put, sd, cfg.num_layers)
    model.eval()
    return model
