"""HuggingFace transformers interop: convert a GPT2LMHeadModel into this
framework's GPTForCausalLM (the migration path for users with existing
torch GPT-2 checkpoints).

Layout notes (verified against transformers' GPT2 state_dict):
 * HF Conv1D stores weights [in, out] — identical to this framework's
   Linear, so qkv/proj/fc weights copy without transpose.
 * c_attn packs [Q|K|V] along the output dim in that order, matching
   GPTAttention's reshape([b, s, 3, H, hd]).
 * HF GPT-2 uses the tanh-approximate gelu ("gelu_new"): the converted
   config sets gelu_approx=True so logits match bit-for-tolerance
   (tests/test_hf_bridge.py pins parity against the torch forward).
"""
import numpy as np

from .gpt import GPTConfig, GPTForCausalLM


def gpt2_from_huggingface(hf_model=None, model_name=None, dtype="float32"):
    """Build a GPTForCausalLM carrying the weights of a transformers
    GPT2LMHeadModel.

    Pass an instantiated `hf_model` (offline-safe), or `model_name` to let
    transformers resolve it (requires the checkpoint in the local HF cache —
    this image has no network egress)."""
    if hf_model is None:
        if model_name is None:
            raise ValueError("pass hf_model= or model_name=")
        from transformers import GPT2LMHeadModel

        hf_model = GPT2LMHeadModel.from_pretrained(model_name)
    hc = hf_model.config
    act = getattr(hc, "activation_function", "gelu_new")
    if act in ("gelu_new", "gelu_pytorch_tanh"):
        gelu_approx = True
    elif act == "gelu":
        gelu_approx = False
    else:
        raise ValueError(f"unsupported activation_function {act!r}; this "
                         "bridge maps gelu_new/gelu_pytorch_tanh/gelu only")
    cfg = GPTConfig(vocab_size=hc.vocab_size, hidden_size=hc.n_embd,
                    num_layers=hc.n_layer, num_heads=hc.n_head,
                    max_seq_len=hc.n_positions,
                    intermediate_size=getattr(hc, "n_inner", None)
                    or 4 * hc.n_embd,
                    dropout=0.0, gelu_approx=gelu_approx)
    model = GPTForCausalLM(cfg)

    sd = {k: v.detach().cpu().numpy().astype(dtype)
          for k, v in hf_model.state_dict().items()}
    ours = dict(model.named_parameters())

    def put(name, arr):
        t = ours[name]
        if tuple(t.shape) != tuple(arr.shape):
            raise ValueError(f"{name}: shape {tuple(arr.shape)} != "
                             f"{tuple(t.shape)}")
        t.set_value(arr)

    put("gpt.wte.weight", sd["transformer.wte.weight"])
    put("gpt.wpe.weight", sd["transformer.wpe.weight"])
    for i in range(cfg.num_layers):
        hf = f"transformer.h.{i}."
        us = f"gpt.blocks.{i}."
        put(us + "ln1.weight", sd[hf + "ln_1.weight"])
        put(us + "ln1.bias", sd[hf + "ln_1.bias"])
        put(us + "attn.qkv.weight", sd[hf + "attn.c_attn.weight"])
        put(us + "attn.qkv.bias", sd[hf + "attn.c_attn.bias"])
        put(us + "attn.proj.weight", sd[hf + "attn.c_proj.weight"])
        put(us + "attn.proj.bias", sd[hf + "attn.c_proj.bias"])
        put(us + "ln2.weight", sd[hf + "ln_2.weight"])
        put(us + "ln2.bias", sd[hf + "ln_2.bias"])
        put(us + "mlp.fc1.weight", sd[hf + "mlp.c_fc.weight"])
        put(us + "mlp.fc1.bias", sd[hf + "mlp.c_fc.bias"])
        put(us + "mlp.fc2.weight", sd[hf + "mlp.c_proj.weight"])
        put(us + "mlp.fc2.bias", sd[hf + "mlp.c_proj.bias"])
    put("gpt.ln_f.weight", sd["transformer.ln_f.weight"])
    put("gpt.ln_f.bias", sd["transformer.ln_f.bias"])
    # lm_head ties to wte in HF GPT-2 exactly like this framework's tied head
    model.eval()
    return model
