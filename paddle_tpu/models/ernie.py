"""ERNIE-1.0 style encoder (BASELINE.json config #4: ERNIE-1.0 / GPT-2 medium).

ERNIE (Enhanced Representation through kNowledge IntEgration) is architecturally a
BERT-family encoder; its distinguishing features are (1) relu FFN activation and the
Chinese-vocab sizing of the original release, (2) knowledge masking — whole-phrase /
whole-entity span masking at the data level rather than token-level masking — and
(3) optional task-type embeddings (ERNIE 2.0 continual pretraining).

The reference trains ERNIE through fleet on the same Transformer blocks
(python/paddle/nn/layer/transformer.py); there is no ernie model file in the
reference tree — this is the framework's own model zoo, built on paddle_tpu.nn.
"""
import numpy as np

from .. import nn
from ..nn import functional as F


class ErnieConfig:
    def __init__(self, vocab_size=18000, hidden_size=768, num_layers=12, num_heads=12,
                 intermediate_size=3072, max_position=513, type_vocab_size=2,
                 task_type_vocab_size=0, dropout=0.1, activation="relu",
                 layer_norm_eps=1e-5):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.task_type_vocab_size = task_type_vocab_size  # >0: ERNIE-2.0 task emb
        self.dropout = dropout
        self.activation = activation
        self.layer_norm_eps = layer_norm_eps

    @staticmethod
    def base():
        return ErnieConfig()

    @staticmethod
    def tiny():
        return ErnieConfig(vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
                           intermediate_size=128, max_position=128, dropout=0.0)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.word = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.token_type = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size)
        self.task_type = (nn.Embedding(cfg.task_type_vocab_size, cfg.hidden_size)
                          if cfg.task_type_vocab_size > 0 else None)
        self.ln = nn.LayerNorm(cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None):
        from ..tensor.creation import arange, zeros_like

        s = input_ids.shape[1]
        pos = arange(s, dtype="int32")  # int32: x64 is off on TPU/CPU — an "int64" request
        # is truncated with a per-call UserWarning (caught by the analysis trace-warnings gate)
        x = self.word(input_ids) + self.position(pos)
        if token_type_ids is None:
            # segment-0 embedding still contributes when ids are omitted
            # (same BERT-family semantics as models/bert.py)
            token_type_ids = zeros_like(input_ids)
        x = x + self.token_type(token_type_ids)
        if self.task_type is not None:
            if task_type_ids is None:
                task_type_ids = zeros_like(input_ids)
            x = x + self.task_type(task_type_ids)
        return self.drop(self.ln(x))


class ErnieModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.dropout, activation=cfg.activation,
        )
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        eps = getattr(cfg, "layer_norm_eps", 1e-5)
        for _, sub in self.named_sublayers(include_self=True):
            if isinstance(sub, nn.LayerNorm):
                sub._epsilon = eps

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        x = self.embeddings(input_ids, token_type_ids, task_type_ids)
        # [b, s] keep-masks normalize inside the shared attention stack
        x = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    """MLM head (weight tied to word embedding) + NSP head, BERT-style."""

    def __init__(self, cfg):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.ln = nn.LayerNorm(cfg.hidden_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)
        self.cfg = cfg

    def forward(self, input_ids, token_type_ids=None):
        seq, pooled = self.ernie(input_ids, token_type_ids)
        h = self.ln(F.gelu(self.transform(seq)))
        from ..tensor.math import matmul

        mlm_logits = matmul(h, self.ernie.embeddings.word.weight, transpose_y=True)
        return mlm_logits, self.nsp(pooled)


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, cfg, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.drop = nn.Dropout(cfg.dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids)
        return self.classifier(self.drop(pooled))


class ErniePretrainLoss(nn.Layer):
    """MLM + NSP joint loss; labels = (mlm_labels, nsp_labels) or mlm only."""

    def forward(self, outputs, labels):
        mlm_logits, nsp_logits = outputs
        if isinstance(labels, (tuple, list)):
            mlm_labels, nsp_labels = labels
        else:
            mlm_labels, nsp_labels = labels, None
        b, s, v = mlm_logits.shape
        loss = F.cross_entropy(mlm_logits.reshape([b * s, v]),
                               mlm_labels.reshape([b * s]), ignore_index=-100)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits, nsp_labels)
        return loss


def knowledge_mask(input_ids, spans, mask_token_id, vocab_size, mask_prob=0.15,
                   rng=None, ignore_index=-100):
    """ERNIE knowledge masking: mask whole spans (phrases/entities), not tokens.

    input_ids: np.ndarray [b, s]; spans: per-example list of (start, end) spans
    covering candidate phrase/entity units. A span is masked with prob
    `mask_prob` — 80% [MASK], 10% random, 10% unchanged, applied to the WHOLE
    span (the ERNIE-1.0 phrase/entity-level strategy). Returns (masked_ids,
    labels) with labels == ignore_index at unmasked positions.
    """
    if rng is None:
        rng = np.random  # global RNG: fresh masking every call/epoch
    ids = np.array(input_ids, copy=True)
    labels = np.full_like(ids, ignore_index)
    for b, ex_spans in enumerate(spans):
        for (start, end) in ex_spans:
            if rng.rand() >= mask_prob:
                continue
            labels[b, start:end] = ids[b, start:end]
            r = rng.rand()
            if r < 0.8:
                ids[b, start:end] = mask_token_id
            elif r < 0.9:
                ids[b, start:end] = rng.randint(0, vocab_size, size=end - start)
            # else: keep original tokens
    return ids, labels


def ernie_base(**kw):
    return ErnieModel(ErnieConfig.base())
