"""High-level Model API.

Reference parity: python/paddle/hapi/model.py (Model:810 — fit:1299, evaluate:1515,
predict:1609, save:1043, load, prepare:1244, train_batch:896; DynamicGraphAdapter:609
and StaticGraphAdapter:224).

TPU-native design: DynamicGraphAdapter = eager tape loop (semantics parity);
JitGraphAdapter (the StaticGraphAdapter analog) compiles the whole train step with
SpmdTrainer — one XLA program incl. optimizer update, batch sharded over the mesh. The
adapter is chosen by paddle_tpu.static mode or Model(..., use_jit=True); both share the
same fit/evaluate/predict driver.
"""
import functools

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..io import DataLoader
from ..metric.metrics import Metric
from . import callbacks as cbks_mod


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class DynamicGraphAdapter:
    """hapi/model.py:609 parity — eager forward/backward/step."""

    def __init__(self, model):
        self.model = model

    def train_batch(self, inputs, labels=None):
        net = self.model.network
        net.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        outputs = net(*inputs)
        losses = self.model._loss(*(_to_list(outputs) + labels)) if self.model._loss else outputs
        loss = losses if isinstance(losses, Tensor) else sum(losses)
        loss.backward()
        self.model._optimizer.step()
        self.model._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return self._return(loss, metrics)

    def eval_batch(self, inputs, labels=None):
        from ..core.tape import no_grad

        net = self.model.network
        net.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        with no_grad():
            outputs = net(*inputs)
            if self.model._loss:
                losses = self.model._loss(*(_to_list(outputs) + labels))
                loss = losses if isinstance(losses, Tensor) else sum(losses)
            else:
                loss = None
        metrics = self._update_metrics(outputs, labels)
        return self._return(loss, metrics)

    def predict_batch(self, inputs):
        from ..core.tape import no_grad

        net = self.model.network
        net.eval()
        with no_grad():
            outputs = net(*_to_list(inputs))
        return [np.asarray(o._data) for o in _to_list(outputs)]

    def _update_metrics(self, outputs, labels):
        vals = []
        for m in self.model._metrics:
            res = m.compute(*(_to_list(outputs) + labels))
            v = m.update(*_to_list(res))
            vals.append(v)
        return vals

    def _return(self, loss, metrics):
        l = [float(np.asarray(loss._data))] if loss is not None else []
        if metrics:
            return (l, metrics) if l else metrics
        return l


class JitGraphAdapter(DynamicGraphAdapter):
    """StaticGraphAdapter:224 analog — whole-step XLA compilation via SpmdTrainer."""

    def __init__(self, model):
        super().__init__(model)
        self._trainer = None
        self._eval_fn = None
        self._eval_synced = False

    def train_batch(self, inputs, labels=None):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        # train mode BEFORE any (re)trace: an eval's net.eval() would
        # otherwise bake dropout-off/BN-frozen into the compiled train step
        self.model.network.train()
        self._eval_synced = False
        if self._trainer is None:
            from ..distributed.spmd import SpmdTrainer

            def loss_fn(out, label):
                outs = _to_list(out)
                return self.model._loss(*(outs + [label]))

            # return_outputs: the jitted step hands back the forward outputs,
            # so metrics never trigger a second (eager) forward per batch
            self._trainer = SpmdTrainer(
                self.model.network, self.model._optimizer, loss_fn,
                return_outputs=bool(self.model._metrics),
            )
        loss = self._trainer.train_step(*(inputs + labels))
        metrics = []
        if self.model._metrics:
            metrics = self._update_metrics(self._trainer.last_outputs, labels)
        return self._return(loss, metrics)

    def eval_batch(self, inputs, labels=None):
        """Jitted eval: forward+loss compile once per shape (the
        StaticGraphAdapter's test program analog) instead of eager per batch."""
        import jax

        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self._trainer is not None and not self._eval_synced:
            # once per eval loop, not per batch (stage-3 sync device_gets
            # every param; train_batch resets the flag)
            self._trainer.sync_to_layer()
            self._eval_synced = True
        net = self.model.network
        net.eval()
        unwrap = functools.partial(
            jax.tree_util.tree_map,
            lambda v: v._data if isinstance(v, Tensor) else v,
            is_leaf=lambda v: isinstance(v, Tensor))
        if self._eval_fn is None:
            from ..core.functional import functional_state
            from ..core.tape import global_tape

            def pure(n_labels, params, buffers, *arrs):
                with functional_state(net, params, buffers), \
                        global_tape().pause():
                    n_in = len(arrs) - n_labels
                    ins = [Tensor(a) for a in arrs[:n_in]]
                    lbs = [Tensor(a) for a in arrs[n_in:]]
                    outputs = net(*ins)
                    loss = None
                    if self.model._loss:
                        losses = self.model._loss(*(_to_list(outputs) + lbs))
                        loss = (losses if isinstance(losses, Tensor)
                                else sum(losses))
                return (loss._data if loss is not None else None), \
                    unwrap(outputs)

            # n_labels is STATIC: a changed input/label split with identical
            # array shapes must re-trace, not replay a stale split
            self._eval_fn = jax.jit(pure, static_argnums=0)
        params = {n: p._data for n, p in net.named_parameters()}
        buffers = {n: b._data for n, b in net.named_buffers()}
        arrs = [x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
                for x in inputs + labels]
        loss_raw, outs_raw = self._eval_fn(len(labels), params, buffers, *arrs)
        outputs = jax.tree_util.tree_map(Tensor, outs_raw)
        loss = Tensor(loss_raw) if loss_raw is not None else None
        metrics = self._update_metrics(outputs, labels)
        return self._return(loss, metrics)

    def predict_batch(self, inputs):
        if self._trainer is not None:
            self._trainer.sync_to_layer()
        return super().predict_batch(inputs)


class Model:
    """paddle.Model parity (hapi/model.py:810)."""

    def __init__(self, network, inputs=None, labels=None, use_jit=False):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self.stop_training = False
        from ..static import in_static_mode

        use_jit = use_jit or in_static_mode()
        self._adapter = JitGraphAdapter(self) if use_jit else DynamicGraphAdapter(self)

    # -- setup -----------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        """hapi/model.py:1244 parity. Re-preparing resets the compiled
        trainer (reference semantics: prepare rebuilds the adapter programs),
        so a metrics change re-compiles with the matching step signature."""
        if isinstance(self._adapter, JitGraphAdapter):
            if self._adapter._trainer is not None:
                self._adapter._trainer.sync_to_layer()
                self._adapter._trainer = None
            self._adapter._eval_fn = None
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be callable (a Layer or function)")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric must be paddle_tpu.metric.Metric, got {type(m)}")
        return self

    # -- batch-level API --------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        return self._adapter.train_batch(inputs, labels)

    def eval_batch(self, inputs, labels=None):
        return self._adapter.eval_batch(inputs, labels)

    def predict_batch(self, inputs):
        return self._adapter.predict_batch(inputs)

    # -- loop API ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """hapi/model.py:1299 parity."""
        train_loader = self._to_loader(train_data, batch_size, shuffle, drop_last, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, False, num_workers) if eval_data is not None else None

        steps = self._len_or_none(train_loader)
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps, log_freq=log_freq,
            verbose=verbose, save_freq=save_freq, save_dir=save_dir,
            metrics=["loss"] + [m.name() for m in self._metrics],
        )
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                if num_iters is not None and step >= num_iters:
                    break
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                res = self.train_batch(inputs, labels)
                logs = self._make_logs(res)
                cbks.on_train_batch_end(step, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                logs.update({"eval_" + k: v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_train_end(logs if "logs" in dir() else None)
        return self

    def _run_eval(self, eval_loader, cbks):
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({"steps": self._len_or_none(eval_loader)})
        logs = {}
        for step, batch in enumerate(eval_loader):
            inputs, labels = self._split_batch(batch)
            res = self.eval_batch(inputs, labels)
            logs = self._make_logs(res)
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        """hapi/model.py:1515 parity."""
        loader = self._to_loader(eval_data, batch_size, False, False, num_workers)
        cbks = cbks_mod.config_callbacks(callbacks, model=self, verbose=verbose,
                                         metrics=["loss"] + [m.name() for m in self._metrics])
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({"steps": self._len_or_none(loader)})
        logs = {}
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            res = self.eval_batch(inputs, labels)
            logs = self._make_logs(res)
            cbks.on_eval_batch_end(step, logs)
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        """hapi/model.py:1609 parity."""
        loader = self._to_loader(test_data, batch_size, False, False, num_workers)
        cbks = cbks_mod.config_callbacks(callbacks, model=self, verbose=verbose)
        cbks.on_predict_begin()
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            inputs, _ = self._split_batch(batch, predict=True)
            out = self.predict_batch(inputs)
            outputs.append(out)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # transpose to per-output lists
        n_out = len(outputs[0]) if outputs else 0
        result = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            result = [np.concatenate(r, axis=0) for r in result]
        return result

    # -- persistence ------------------------------------------------------------
    def save(self, path, training=True):
        """hapi/model.py:1043 parity."""
        from ..framework.io import save as psave

        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                psave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from .. import jit

            jit.save(self.network, path)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        import os

        state = pload(path + ".pdparams" if not path.endswith(".pdparams") else path)
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(pload(opt_path))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        if input_size is None and self._inputs:
            # reference fallback: use the InputSpec list given to Model()
            input_size = [tuple(s.shape) for s in self._inputs]
            if dtype is None:
                dtype = [str(getattr(s, "dtype", None) or "float32")
                         for s in self._inputs]
        return summary(self.network, input_size, dtypes=dtype)

    # -- helpers ----------------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    @staticmethod
    def _len_or_none(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _split_batch(self, batch, predict=False):
        batch = batch if isinstance(batch, (list, tuple)) else [batch]
        if predict:
            # datasets that yield (x, label): drop the label when a loss was prepared
            if self._loss is not None and len(batch) > 1:
                return list(batch[:-1]), []
            return list(batch), []
        if len(batch) == 1:
            return [batch[0]], []
        return list(batch[:-1]), [batch[-1]]

    def _make_logs(self, res):
        logs = {}
        if isinstance(res, tuple) and len(res) == 2:
            losses, metrics = res
            if losses:
                logs["loss"] = losses[0]
            for m, v in zip(self._metrics, metrics):
                names = m.name() if isinstance(m.name(), list) else [m.name()]
                vals = v if isinstance(v, (list, tuple)) else [v]
                for n, val in zip(names, vals):
                    logs[n] = float(np.asarray(val).mean()) if val is not None else None
            # use accumulated values for stable display
            for m in self._metrics:
                names = m.name() if isinstance(m.name(), list) else [m.name()]
                accs = m.accumulate()
                accs = accs if isinstance(accs, (list, tuple)) else [accs]
                for n, a in zip(names, accs):
                    logs[n] = a
        elif isinstance(res, list) and res:
            logs["loss"] = res[0]
        return logs


def flops(net, input_size, custom_ops=None, print_detail=False):
    """paddle.flops parity (reference hapi/dynamic_flops.py): per-layer
    FLOP counts from the same forward-hook pass that powers summary —
    conv / linear / attention families counted from hooked shapes;
    custom_ops maps a Layer class to fn(layer, input_shape, output_shape)
    -> flops for anything else. print_detail prints the per-layer table."""
    from .model_summary import summary_string

    _, info = summary_string(net, input_size=input_size)
    total = 0
    rows = []
    for r in info["records"]:
        f = r["flops"]
        if custom_ops:
            fn = custom_ops.get(type(r["layer"]))
            if fn is not None:
                f = int(fn(r["layer"], r["input_shape"], r["output_shape"]))
        total += f
        rows.append((r["key"], r["input_shape"], r["output_shape"],
                     r["nb_params"], f))
    if print_detail:
        w = max([12] + [len(k) for k, *_ in rows])
        print(f"{'Layer':<{w}}  {'Input Shape':<22}{'Output Shape':<22}"
              f"{'Params':>12}{'FLOPs':>16}")
        print("-" * (w + 74))
        for k, i, o, p, f in rows:
            print(f"{k:<{w}}  {str(i):<22}{str(o):<22}{p:>12,}{f:>16,}")
        print("-" * (w + 74))
        print(f"Total FLOPs: {total:,}")
    return int(total)
