"""Model summary (python/paddle/hapi/model_summary.py parity)."""
import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=False):
        n_params = sum(p.size for p in layer._parameters.values() if p is not None)
        total_params_layer = n_params
        rows.append((name or layer.__class__.__name__, layer.__class__.__name__, total_params_layer))
    for p in net.parameters():
        total_params += p.size
        if getattr(p, "trainable", True):
            trainable_params += p.size
    print("-" * 64)
    print(f"{'Layer':<30}{'Type':<22}{'Params':>10}")
    print("=" * 64)
    for name, typ, n in rows:
        print(f"{name:<30}{typ:<22}{n:>10,}")
    print("=" * 64)
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable_params:,}")
    print(f"Non-trainable params: {total_params - trainable_params:,}")
    print("-" * 64)
    return {"total_params": total_params, "trainable_params": trainable_params}
