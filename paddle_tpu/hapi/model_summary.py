"""Model summary — full parity with the reference's hook-driven table
(python/paddle/hapi/model_summary.py: per-layer input/output shapes via
forward hooks, trainable split, memory-estimate footer), built on this
framework's own Layer hook API.

`summary_string` also powers `paddle.flops(..., print_detail=True)`:
per-layer FLOP counts are derived from the hooked shapes for the matmul-
bearing layers (conv / linear / attention), the reference's
hapi/dynamic_flops.py role.
"""
import numbers

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary", "summary_string"]


def _normalize_shape(shape):
    """Replace a single batch None/-1 with 1; validate the rest positive."""
    unknown = 0
    out = []
    for d in shape:
        if d is None or (isinstance(d, numbers.Number) and d == -1):
            unknown += 1
            if unknown > 1:
                raise ValueError(
                    "input_size: only the batch dim may be None or -1")
            out.append(1)
        else:
            d = int(d)
            if d <= 0:
                raise ValueError(f"input_size dims must be positive, got {d}")
            out.append(d)
    return tuple(out)


def _is_plain_shape(s):
    return isinstance(s, (list, tuple)) and all(
        isinstance(d, numbers.Number) or d is None for d in s)


def _build_inputs(input_size, dtypes):
    """input_size: tuple | InputSpec | list of those → list of Tensors."""
    specs = []

    def collect(sz):
        if hasattr(sz, "shape"):                      # InputSpec
            specs.append((_normalize_shape(sz.shape),
                          str(getattr(sz, "dtype", None) or "float32")))
        elif _is_plain_shape(sz):
            specs.append((_normalize_shape(sz), None))
        elif isinstance(sz, (list, tuple)):
            for item in sz:
                collect(item)
        else:
            raise TypeError(f"unsupported input_size entry {sz!r}")

    collect(input_size)
    if dtypes is not None:
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes]
        specs = [(sh, str(dts[min(i, len(dts) - 1)]))
                 for i, (sh, _) in enumerate(specs)]
    rng = np.random.RandomState(0)
    out = []
    for sh, dt in specs:
        dt = np.dtype(dt or "float32")
        if np.issubdtype(dt, np.floating):
            out.append(Tensor(rng.rand(*sh).astype(dt)))
        else:
            out.append(Tensor(np.zeros(sh, dt)))
    return out


def _shape_of(x):
    if isinstance(x, (list, tuple)):
        return [_shape_of(v) for v in x]
    return list(getattr(x, "shape", []))


def _numel(shape_tree):
    if not shape_tree:
        return 0
    if isinstance(shape_tree[0], list):
        return sum(_numel(s) for s in shape_tree)
    return int(np.prod(shape_tree))


def _layer_flops(layer, in_shapes, out_shapes):
    """FLOPs for the matmul-bearing layer families, from hooked shapes
    (multiply-accumulate = 2 FLOPs, the convention the MFU numbers use)."""
    cls = type(layer).__name__
    try:
        if cls.startswith("Conv") and getattr(layer, "weight", None) \
                is not None:
            w = layer.weight.shape          # [Cout, Cin/g, *k]
            out = out_shapes if not isinstance(out_shapes[0], list) \
                else out_shapes[0]
            return 2 * int(np.prod(w)) * int(np.prod(out[2:])) * out[0]
        if cls == "Linear" and getattr(layer, "weight", None) is not None:
            w = layer.weight.shape          # [in, out]
            ins = in_shapes if not isinstance(in_shapes[0], list) \
                else in_shapes[0]
            batch_elems = int(np.prod(ins[:-1])) if len(ins) > 1 else 1
            return 2 * batch_elems * int(np.prod(w))
        if hasattr(layer, "num_heads") and hasattr(layer, "head_dim"):
            # attention core: QK^T and PV, 2*b*s_q*s_kv*h each (the
            # q/k/v/out projections are Linear sublayers, counted above);
            # cross-attention takes s_kv from the key input when present
            if isinstance(in_shapes[0], list):
                q = in_shapes[0]
                kv = in_shapes[1] if len(in_shapes) > 1 \
                    and isinstance(in_shapes[1], list) \
                    and len(in_shapes[1]) >= 3 else q
            else:
                q = kv = in_shapes
            if len(q) >= 3:
                h = layer.num_heads * layer.head_dim
                return 4 * q[0] * q[1] * kv[1] * h
    except Exception:
        pass
    return 0


def summary_string(model, input_size=None, dtypes=None, input=None):
    """Build the summary table. Returns (table_str, params_info);
    params_info carries the totals AND the per-layer records (paddle.flops
    reuses them for its per-layer detail table)."""
    if input is not None:
        xs = input if isinstance(input, (list, tuple)) else [input]
        xs = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
              for x in xs]
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        xs = _build_inputs(input_size, dtypes)

    records = []     # per executed leaf layer, in execution order
    hooks = []
    container_types = {"Sequential", "LayerList", "ParameterList"}

    def register(layer):
        cls = type(layer).__name__
        if layer is model and list(model.sublayers()):
            return
        if cls in container_types:
            return

        def hook(lyr, inputs, output, _cls=cls):
            n_params = 0
            trainable = False
            for p in lyr._parameters.values():
                if p is None:
                    continue
                n_params += int(p.size)
                if getattr(p, "trainable", True) and \
                        not getattr(p, "stop_gradient", False):
                    trainable = True
            in_sh = _shape_of(list(inputs) if len(inputs) != 1
                              else inputs[0])
            out_sh = _shape_of(output if not isinstance(output, tuple)
                               or len(output) != 1 else output[0])
            records.append({
                "key": f"{_cls}-{len(records) + 1}", "layer": lyr,
                "input_shape": in_sh, "output_shape": out_sh,
                "nb_params": n_params, "trainable": trainable,
                "flops": _layer_flops(lyr, in_sh, out_sh),
            })

        hooks.append(layer.register_forward_post_hook(hook))

    was_training = model.training
    model.eval()
    try:
        model.apply(register)
        model(*xs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            model.train()

    # column widths stretch to content (reference layout)
    w_layer = max([15] + [len(r["key"]) for r in records])
    w_in = max([20] + [len(str(r["input_shape"])) for r in records])
    w_out = max([20] + [len(str(r["output_shape"])) for r in records])
    w_par = max([15] + [len(f"{r['nb_params']:,}") for r in records])
    w_table = w_layer + w_in + w_out + w_par + 5

    lines = ["-" * w_table,
             f"{'Layer (type)':^{w_layer}} {'Input Shape':^{w_in}} "
             f"{'Output Shape':^{w_out}} {'Param #':^{w_par}}",
             "=" * w_table]
    total_output_elems = 0
    for r in records:
        lines.append(
            f"{r['key']:^{w_layer}} {str(r['input_shape']):^{w_in}} "
            f"{str(r['output_shape']):^{w_out}} "
            f"{'{:,}'.format(r['nb_params']):^{w_par}}")
        total_output_elems += _numel(r["output_shape"])

    # totals from parameters() directly — NOT from the hook records, which
    # miss root-level params and double-count weight-shared layers
    total_params = trainable_params = 0
    seen = set()
    for p in model.parameters():
        if id(p) in seen:
            continue
        seen.add(id(p))
        total_params += int(p.size)
        if getattr(p, "trainable", True) and \
                not getattr(p, "stop_gradient", False):
            trainable_params += int(p.size)

    input_elems = sum(int(np.prod(x.shape)) for x in xs)
    input_mb = input_elems * 4.0 / (1024 ** 2)
    # x2: forward activations + their gradients (reference convention)
    output_mb = 2.0 * total_output_elems * 4.0 / (1024 ** 2)
    params_mb = total_params * 4.0 / (1024 ** 2)

    lines += ["=" * w_table,
              f"Total params: {total_params:,}",
              f"Trainable params: {trainable_params:,}",
              f"Non-trainable params: {total_params - trainable_params:,}",
              "-" * w_table,
              f"Input size (MB): {input_mb:.2f}",
              f"Forward/backward pass size (MB): {output_mb:.2f}",
              f"Params size (MB): {params_mb:.2f}",
              f"Estimated Total Size (MB): "
              f"{input_mb + output_mb + params_mb:.2f}",
              "-" * w_table]
    info = {"total_params": int(total_params),
            "trainable_params": int(trainable_params),
            "records": records}
    return "\n".join(lines) + "\n", info


def summary(net, input_size=None, dtypes=None, input=None):
    """Print the per-layer summary table; returns
    {'total_params', 'trainable_params'} (reference return contract)."""
    text, info = summary_string(net, input_size, dtypes=dtypes, input=input)
    print(text)
    return {"total_params": info["total_params"],
            "trainable_params": info["trainable_params"]}
