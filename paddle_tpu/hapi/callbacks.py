"""Training callbacks (python/paddle/hapi/callbacks.py parity: Callback, ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL->TensorBoard-style writer,
ReduceLROnPlateau)."""
import numbers
import os

import numpy as np

from .progressbar import ProgressBar


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = callbacks if callbacks is not None else []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return lst


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for c in self.callbacks:
            fn = getattr(c, name, None)
            if fn:
                fn(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_eval_begin(self, logs=None):
        self._call("on_eval_begin", logs)

    def on_eval_end(self, logs=None):
        self._call("on_eval_end", logs)

    def on_predict_begin(self, logs=None):
        self._call("on_predict_begin", logs)

    def on_predict_end(self, logs=None):
        self._call("on_predict_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, step, logs=None):
        self._call("on_train_batch_begin", step, logs)

    def on_train_batch_end(self, step, logs=None):
        self._call("on_train_batch_end", step, logs)

    def on_eval_batch_begin(self, step, logs=None):
        self._call("on_eval_batch_begin", step, logs)

    def on_eval_batch_end(self, step, logs=None):
        self._call("on_eval_batch_end", step, logs)

    def on_predict_batch_begin(self, step, logs=None):
        self._call("on_predict_batch_begin", step, logs)

    def on_predict_batch_end(self, step, logs=None):
        self._call("on_predict_batch_end", step, logs)


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.progbar = ProgressBar(num=self.steps, verbose=self.verbose)
        self.seen = 0

    def _values(self, logs):
        return [(k, v) for k, v in (logs or {}).items() if isinstance(v, numbers.Number)]

    def on_train_batch_end(self, step, logs=None):
        self.seen += 1
        if self.seen % self.log_freq == 0 and self.verbose:
            self.progbar.update(self.seen, self._values(logs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self.progbar.update(self.seen, self._values(logs))

    def on_eval_begin(self, logs=None):
        self.eval_progbar = ProgressBar(num=(logs or {}).get("steps"), verbose=self.verbose)
        self.eval_seen = 0

    def on_eval_batch_end(self, step, logs=None):
        self.eval_seen += 1

    def on_eval_end(self, logs=None):
        if self.verbose:
            print("Eval - " + " - ".join(f"{k}: {v}" for k, v in self._values(logs)))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        if opt and isinstance(opt._lr, Sched):
            return opt._lr
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = np.greater
        else:
            self.monitor_op = np.less
        self.best = None
        self.wait = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.best is None or self.monitor_op(current - self.min_delta, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """VisualDL writer parity (hapi/callbacks.py VisualDL): streams train
    scalars in the standard TF events wire format that BOTH VisualDL and
    TensorBoard read (utils/tb_writer.py — no visualdl/tensorboard dep in
    image), plus a human-greppable scalars.tsv alongside."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None
        self._events = None
        self._step = 0

    def on_train_begin(self, logs=None):
        from ..utils.tb_writer import EventFileWriter

        self._f = open(os.path.join(self.log_dir, "scalars.tsv"), "a")
        self._events = EventFileWriter(os.path.join(self.log_dir, "train"))

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                self._f.write(f"{self._step}\t{k}\t{v}\n")
                self._events.add_scalar(f"train/{k}", float(v), self._step)

    def on_epoch_end(self, epoch, logs=None):
        if self._events:
            self._events.flush()

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number) and self._events:
                self._events.add_scalar(f"eval/{k}", float(v), self._step)

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
        if self._events:
            self._events.close()


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1, mode="auto",
                 min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        from ..optimizer.lr import ReduceOnPlateau as _R

        self.monitor = monitor
        self._impl_args = dict(factor=factor, patience=patience, cooldown=cooldown, min_lr=min_lr)

    def on_eval_end(self, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        # simple plateau: reduce when not improving
        if not hasattr(self, "_best") or current < self._best - 1e-9:
            self._best = current
            self._wait = 0
        else:
            self._wait = getattr(self, "_wait", 0) + 1
            if self._wait > self._impl_args["patience"]:
                try:
                    opt.set_lr(max(opt.get_lr() * self._impl_args["factor"], self._impl_args["min_lr"]))
                except RuntimeError:
                    pass
                self._wait = 0
