"""Progress bar (python/paddle/hapi/progressbar.py parity, simplified terminal output)."""
import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True, file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self._file = file
        self._start = time.time()
        self._last_update = 0

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        now = time.time()
        metrics = " - ".join(
            f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
            for k, v in (values or [])
        )
        if self._num:
            msg = f"step {current_num}/{self._num} - {metrics}"
        else:
            msg = f"step {current_num} - {metrics}"
        if self._verbose == 1:
            self._file.write("\r" + msg)
            if self._num and current_num >= self._num:
                self._file.write("\n")
        elif self._verbose == 2 and (self._num is None or current_num >= self._num or now - self._last_update > 10):
            self._file.write(msg + "\n")
        self._last_update = now
        self._file.flush()
