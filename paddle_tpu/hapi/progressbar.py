"""Progress bar (python/paddle/hapi/progressbar.py parity): the reference's
keras-style training display — `step  3/10 [=====>....]` bar with metric
values, adaptive s/ms/us-per-step rate, ETA, terminal-width clamp, and the
three verbosity modes (1 = in-place dynamic bar, 2/3 = one line per update,
0 = silent). Unknown totals (num=None) print one line per update even at
verbose=1 — the reference's own behavior (no in-place bar without a
total)."""
import os
import shutil
import sys
import time

import numpy as np


def _fmt_value(v):
    if isinstance(v, (float, np.floating)):
        return f" {v:.4f}" if abs(v) > 1e-3 else f" {v:.4e}"
    if isinstance(v, np.ndarray) and v.size == 1 and \
            np.issubdtype(v.dtype, np.floating):
        x = float(v.reshape(()))
        return f" {x:.4f}" if abs(x) > 1e-3 else f" {x:.4e}"
    return f" {v}"


def _fmt_values(values):
    info = ""
    for k, val in (values or []):
        info += f" - {k}:"
        for v in (val if isinstance(val, list) else [val]):
            info += _fmt_value(v)
    return info


def _fmt_eta(eta):
    if eta > 3600:
        return f"{int(eta // 3600)}:{int(eta % 3600 // 60):02d}:" \
               f"{int(eta % 60):02d}"
    if eta > 60:
        return f"{int(eta // 60)}:{int(eta % 60):02d}"
    return f"{int(eta)}s"


def _fmt_rate(time_per_unit):
    if time_per_unit >= 1 or time_per_unit == 0:
        return f" - {time_per_unit:.0f}s/step"
    if time_per_unit >= 1e-3:
        return f" - {time_per_unit * 1e3:.0f}ms/step"
    return f" - {time_per_unit * 1e6:.0f}us/step"


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        if isinstance(num, int) and num <= 0:
            raise TypeError("num should be None or a positive integer")
        self._num = num
        self._verbose = verbose
        self._file = file
        # clamp the bar to the terminal so counter + metrics fit on one
        # line — but only when actually writing to the controlling
        # terminal; explicit files keep the requested width (deterministic
        # output regardless of the ambient COLUMNS)
        if file in (sys.stdout, sys.stderr):
            term_w = shutil.get_terminal_size((80, 24)).columns or 80
            width = min(width, max(int(term_w * 0.6), 10),
                        term_w - 50 if term_w > 60 else width)
        self._width = width
        self._total_width = 0
        self._start = time.time()
        self._dynamic = (hasattr(file, "isatty") and file.isatty()) \
            or "PYCHARM_HOSTED" in os.environ or "ipykernel" in sys.modules

    def start(self):
        self._file.flush()
        self._start = time.time()

    def _bar(self, current_num):
        if self._num is None:
            return f"step {current_num:3d}"
        digits = len(str(self._num))
        head = f"step {current_num:{digits}d}/{self._num} ["
        frac = min(float(current_num) / self._num, 1.0)
        filled = int(self._width * frac)
        body = ""
        if filled > 0:
            body += "=" * (filled - 1)
            body += "=" if current_num >= self._num else ">"
        body += "." * (self._width - filled)
        return head + body + "]"

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        now = time.time()
        time_per_unit = (now - self._start) / current_num if current_num \
            else 0
        info = _fmt_values(values)

        if self._verbose == 1:
            prev_width = self._total_width
            if self._dynamic:
                self._file.write("\r")
            elif prev_width > 0:  # newline separates lines, not a leading one
                self._file.write("\n")
            line = self._bar(current_num) + info
            if self._num is not None and current_num < self._num:
                line += " - ETA: " \
                    + _fmt_eta(time_per_unit * (self._num - current_num))
            line += _fmt_rate(time_per_unit)
            self._total_width = len(line)
            if prev_width > self._total_width:   # erase the longer old line
                line += " " * (prev_width - self._total_width)
            if self._num is None or current_num >= self._num:
                line += "\n"
            self._file.write(line)
        else:   # verbose 2/3: one full line per update
            self._file.write(self._bar(current_num).split(" [")[0] + info
                             + _fmt_rate(time_per_unit) + "\n")
        self._file.flush()
