"""paddle.hapi parity."""
from . import callbacks  # noqa: F401
from .model import Model, flops  # noqa: F401
from .model_summary import summary  # noqa: F401
