"""Lockstep A/B loss-parity harness (docs/OBSERVABILITY.md "Numerics
telescope").

The acceptance question every numerics-affecting change must answer —
mixed precision, the PR 4 guard, ROADMAP item 2's quantized all-reduce —
is "does training still converge the same?". This harness answers it
mechanically: build the SAME model twice (identical seed), once under a
*reference* flag-set/config and once under a *candidate* one, drive both
trainers lockstep over IDENTICAL batches, and assert the per-step loss
and per-layer gradient statistics stay within *declared* tolerances.

The grad stats come from the numerics telescope
(:mod:`paddle_tpu.monitor.numerics`): the harness arms ``FLAGS_numerics``
with ``numerics_interval=1`` around both sides, so every step's fused
on-device per-layer stats are fetched and compared — a change that keeps
the loss curve but silently rewrites one layer's gradient flow diverges
here, not three days into a run.

    from paddle_tpu.testing import parity

    report = parity.run_parity(
        build,                      # () -> SpmdTrainer, called per side
        batches,                    # [(x, y), ...] — identical for both
        candidate_flags={"check_nan_inf": True},
        loss_rtol=0.0, loss_atol=0.0)      # declared tolerance: exact
    parity.assert_parity(report)           # raises naming step + stat

``tools/parity_check.py`` is the CLI form (graph_lint JSON schema, exit
1 on divergence) and is the acceptance gate handed to ROADMAP item 2's
quantized collectives: run the quantized flag-set as the candidate with
its declared loss band and ship only when this passes.
"""
import contextlib

import numpy as np

from .. import flags as _flags

__all__ = [
    "ParityDivergence", "flag_scope", "run_lockstep", "compare_traces",
    "run_parity", "assert_parity", "STAT_COMPARE_KEYS",
]

#: per-layer stat families compared step-by-step (a subset of
#: monitor/numerics.py STAT_KEYS — the scale-free ones that make
#: cross-config comparison meaningful)
STAT_COMPARE_KEYS = ("grad_norm", "update_ratio", "grad_absmax")


class ParityDivergence(AssertionError):
    """A lockstep A/B left its declared tolerance band. The message (and
    ``.divergence`` attribute) name the first diverging step and stat."""

    def __init__(self, message, divergence=None):
        super().__init__(message)
        self.divergence = divergence


@contextlib.contextmanager
def flag_scope(flags):
    """Set FLAGS_* for the with-block, restoring previous values on
    exit. Flags the block INTRODUCED (not yet defined — e.g. a detector
    knob whose lazily-imported module hasn't loaded) are un-defined
    again, so one side's candidate config can never leak into the other
    side — or the next run_parity — through define_flag's
    existing-value-wins rule."""
    flags = {k[6:] if k.startswith("FLAGS_") else k: v
             for k, v in (flags or {}).items()}
    saved = {k: _flags.get_flag(k) for k in flags
             if k in _flags._REGISTRY}
    introduced = [k for k in flags if k not in _flags._REGISTRY]
    _flags.set_flags(flags)
    try:
        yield
    finally:
        _flags.set_flags(saved)
        for k in introduced:
            _flags._REGISTRY.pop(k, None)


def run_lockstep(build, batches, flags=None, seed=0):
    """Run one side of the A/B: under `flags` (+ the forced numerics
    arming), seed, build a trainer via ``build()``, and drive it over
    `batches` (each a tuple/list of per-step arrays). Returns the trace
    {"loss": [float/step], "stats": [{stat: np.ndarray}/step],
    "layers": [param names]}."""
    import paddle_tpu as paddle

    merged = dict(flags or {})
    merged.setdefault("numerics", True)
    merged.setdefault("numerics_interval", 1)
    with flag_scope(merged):
        paddle.seed(seed)
        trainer = build()
        # sorted — the row order of the trainer's numerics stats legs
        trace = {"loss": [], "stats": [], "layers": sorted(trainer.params)}
        for batch in batches:
            loss = trainer.train_step(*batch)
            trace["loss"].append(float(np.asarray(loss._data)))
            host = trainer.numerics_fetch()
            trace["stats"].append(
                {k: np.array(host[k], copy=True)
                 for k in STAT_COMPARE_KEYS} if host else {})
    return trace


def _in_band(ref, cand, rtol, atol):
    if np.isnan(ref) and np.isnan(cand):
        return True
    if not (np.isfinite(ref) and np.isfinite(cand)):
        return ref == cand
    return abs(cand - ref) <= atol + rtol * abs(ref)


def compare_traces(ref, cand, loss_rtol=0.0, loss_atol=0.0,
                   stat_rtol=None, stat_atol=None):
    """Step-by-step comparison of two run_lockstep traces. Returns a
    report dict; ``report["first_divergence"]`` names the earliest
    out-of-band (step, stat, layer) or is None. Stat tolerances default
    to the loss ones (widened ×10 — per-layer norms wobble more than
    their aggregate)."""
    stat_rtol = 10.0 * loss_rtol if stat_rtol is None else stat_rtol
    stat_atol = 10.0 * loss_atol if stat_atol is None else stat_atol
    steps = min(len(ref["loss"]), len(cand["loss"]))
    layers = ref["layers"]
    first = None
    max_loss_diff = 0.0
    for i in range(steps):
        lr_, lc = ref["loss"][i], cand["loss"][i]
        if np.isfinite(lr_) and np.isfinite(lc):
            max_loss_diff = max(max_loss_diff, abs(lc - lr_))
        if not _in_band(lr_, lc, loss_rtol, loss_atol):
            first = {"step": i, "stat": "loss", "layer": None,
                     "reference": lr_, "candidate": lc,
                     "abs_diff": abs(lc - lr_)}
            break
        sr, sc = ref["stats"][i], cand["stats"][i]
        for stat in STAT_COMPARE_KEYS:
            if stat not in sr or stat not in sc:
                continue
            for j, layer in enumerate(layers):
                rv, cv = float(sr[stat][j]), float(sc[stat][j])
                if not _in_band(rv, cv, stat_rtol, stat_atol):
                    first = {"step": i, "stat": stat, "layer": layer,
                             "reference": rv, "candidate": cv,
                             "abs_diff": abs(cv - rv)}
                    break
            if first:
                break
        if first:
            break
    return {
        "steps": steps,
        "diverged": first is not None,
        "first_divergence": first,
        "max_abs_loss_diff": max_loss_diff,
        "tolerances": {"loss_rtol": loss_rtol, "loss_atol": loss_atol,
                       "stat_rtol": stat_rtol, "stat_atol": stat_atol},
    }


def run_parity(build, batches, build_candidate=None, reference_flags=None,
               candidate_flags=None, loss_rtol=0.0, loss_atol=0.0,
               stat_rtol=None, stat_atol=None, seed=0):
    """The whole A/B: reference side (``build`` under
    ``reference_flags``) vs candidate side (``build_candidate`` or the
    same ``build``, under ``candidate_flags``), lockstep over identical
    `batches`, compared within the declared tolerances. Returns the
    compare_traces report, annotated with both flag-sets and both loss
    curves."""
    ref = run_lockstep(build, batches, flags=reference_flags, seed=seed)
    cand = run_lockstep(build_candidate or build, batches,
                        flags=candidate_flags, seed=seed)
    report = compare_traces(ref, cand, loss_rtol=loss_rtol,
                            loss_atol=loss_atol, stat_rtol=stat_rtol,
                            stat_atol=stat_atol)
    report["flags"] = {"reference": dict(reference_flags or {}),
                       "candidate": dict(candidate_flags or {})}
    report["loss"] = {"reference": ref["loss"], "candidate": cand["loss"]}
    return report


def assert_parity(report):
    """Raise :class:`ParityDivergence` naming the first diverging step
    and stat when the report diverged; return the report otherwise."""
    if not report.get("diverged"):
        return report
    d = report["first_divergence"]
    where = d["stat"] + (f"[{d['layer']}]" if d.get("layer") else "")
    raise ParityDivergence(
        f"A/B loss parity diverged at step {d['step']} on {where}: "
        f"reference={d['reference']:.6g} candidate={d['candidate']:.6g} "
        f"(|diff|={d['abs_diff']:.3g}, tolerances "
        f"{report['tolerances']})", divergence=d)
