"""Registered fault-injection framework (docs/ROBUSTNESS.md).

Production faults — a process killed mid-checkpoint, a compile that dies, a
slot whose host-side bookkeeping throws — are rare exactly when you test and
common exactly when you ship. This module plants named *failpoint sites* in
the runtime's recovery-critical paths so chaos tests (and tools/chaos_check.py)
can make those faults happen on demand:

    from paddle_tpu.testing import failpoints

    with failpoints.scoped("ckpt/write=error:1"):
        paddle.save(state, path)          # raises FailpointError once

or process-wide via the flag (parsed at import, re-appliable after
``paddle.set_flags`` with :func:`arm_from_flag`)::

    FLAGS_failpoints="ckpt/write=error:1,serving/step=delay:5" python train.py

Spec syntax: ``site=action[,site=action...]`` with actions

- ``error`` / ``error:N`` — raise :class:`FailpointError` at the site (N
  times, then the site auto-disarms; no N = every hit);
- ``delay:MS`` — sleep MS milliseconds per hit (latency injection);
- ``kill`` — SIGKILL the process at the site (crash-mid-operation tests, in
  the spirit of tests/test_auto_checkpoint_kill.py).

Discipline: **disabled is one boolean check** — the same bar as
``monitor.is_enabled()``, pinned by tests/test_failpoints_gate.py (<5µs/call
and zero behavior/metric drift with nothing armed). Sites are REGISTERED
(the ``SITES`` table below); arming a typo'd name raises with the known list.
"""
import contextlib
import os
import signal
import threading
import time

from .. import flags as _flags
from .. import monitor as _monitor

__all__ = [
    "SITES", "FailpointError", "failpoint", "transform", "arm", "disarm",
    "reset", "armed", "hits", "is_enabled", "scoped", "parse",
    "arm_from_flag",
]

_flags.define_flag(
    "failpoints", "",
    "fault-injection spec 'site=action[,site=action...]' with actions "
    "error[:N] | delay:MS | kill; empty = every failpoint site is a single "
    "boolean check (see paddle_tpu/testing/failpoints.py SITES)")

#: every plantable site, registered centrally so arming a typo fails fast.
SITES = {
    "ckpt/write": "framework.io.save — payload written to the tmp file, "
                  "before the integrity footer + atomic commit",
    "ckpt/read": "framework.io.load — before the checkpoint file is read",
    "ckpt/commit": "CheckpointSaver.save_checkpoint — before the checkpoint "
                   "dir renames into place",
    "exe/compile": "static.Executor._compile — before building/compiling "
                   "the program",
    "collective/call": "distributed.collective — every collective API call",
    "serving/step": "ServingEngine.step — top of the engine step loop",
    "serving/slot": "ServingEngine per-slot host work — isolated: an "
                    "injected error finishes only that slot's request "
                    "(reason='error'), batch-mates continue",
    "trainer/step": "SpmdTrainer.train_step — before the compiled step "
                    "dispatches",
    "trainer/batch": "SpmdTrainer.train_step — the batch arrays on their "
                     "way into the compiled step; a scale:F action "
                     "multiplies every FLOAT array by F (scale:nan "
                     "poisons them) so chaos tests can inject a gradient "
                     "spike or a non-finite step with real data flow "
                     "(integer arrays — token ids — pass untouched)",
    "federated/round": "federated.FederatedAverager — each client's local "
                       "update inside a round; an injected error drops "
                       "that client (federated_client_dropped_total) and "
                       "the round completes with the surviving cohort",
    "stage/edge": "distributed.stage.StageEdge.put — inside the edge's "
                  "blackbox progress window, before the payload is "
                  "validated/encoded onto the queue; a delay here reads "
                  "as a stalled stage to the stall sentinel, an error "
                  "leaves the payload un-enqueued (producer retries)",
    "serving/adapter": "ServingEngine.load_adapter/evict_adapter — before "
                       "the adapter registry or the device factors "
                       "mutate; an injected error leaves both exactly as "
                       "they were (in-flight sessions keep decoding)",
    "stage/run": "distributed.stage.StageProgram.__call__ — before the "
                 "compiled stage dispatches; an injected error reads as "
                 "one stage's slice dying mid-schedule, the trigger for "
                 "MpmdPipelineRunner.replace_stage elasticity "
                 "(tools/chaos_check.py stage_replace)",
    "elastic/resume": "distributed.elastic.ElasticSupervisor — before "
                      "each recovery attempt rebuilds a trainer and "
                      "restores the latest checkpoint; an error here "
                      "consumes one retry from the backoff budget "
                      "(retry-exhaustion tests)",
}


class FailpointError(RuntimeError):
    """The injected fault. Distinct from organic errors so recovery paths
    can be asserted to have handled *this* failure."""


class _Action:
    __slots__ = ("kind", "arg", "remaining")

    def __init__(self, kind, arg=None, remaining=None):
        self.kind = kind            # "error" | "delay" | "kill"
        self.arg = arg              # delay ms
        self.remaining = remaining  # None = unlimited

    def spec(self):
        if self.kind == "delay":
            return f"delay:{self.arg:g}"
        if self.kind == "scale":
            return f"scale:{self.arg:g}"
        if self.kind == "error" and self.remaining is not None:
            return f"error:{self.remaining}"
        return self.kind


_LOCK = threading.RLock()
_ENABLED = False    # the ONE read on the disabled fast path
_ARMED = {}         # site -> _Action
_HITS = {}          # site -> fire count since last reset()
_TRIG = None        # lazy failpoint_trigger_total counter


def _parse_action(site, text):
    kind, _, arg = text.partition(":")
    kind = kind.strip()
    if kind == "error":
        n = None
        if arg:
            n = int(arg)
            if n < 1:
                raise ValueError(f"failpoint {site}: error count must be "
                                 f">= 1, got {n}")
        return _Action("error", remaining=n)
    if kind == "delay":
        if not arg:
            raise ValueError(f"failpoint {site}: delay needs milliseconds "
                             "(delay:MS)")
        ms = float(arg)
        if ms < 0:
            raise ValueError(f"failpoint {site}: delay must be >= 0 ms")
        return _Action("delay", arg=ms)
    if kind == "kill":
        return _Action("kill")
    if kind == "scale":
        if not arg:
            raise ValueError(f"failpoint {site}: scale needs a factor "
                             "(scale:F — float('nan') poisons)")
        return _Action("scale", arg=float(arg))   # float() accepts 'nan'
    raise ValueError(f"failpoint {site}: unknown action {text!r} "
                     "(expected error[:N] | delay:MS | scale:F | kill)")


def parse(spec):
    """Parse a ``site=action,site=action`` spec string into
    {site: _Action}; validates site names against :data:`SITES`."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        site, sep, action = part.partition("=")
        site = site.strip()
        if not sep or not action.strip():
            raise ValueError(f"failpoint spec {part!r}: expected "
                             "site=action")
        if site not in SITES:
            raise ValueError(f"unknown failpoint site {site!r}; known "
                             f"sites: {', '.join(sorted(SITES))}")
        out[site] = _parse_action(site, action.strip())
    return out


def _refresh_enabled():
    global _ENABLED
    _ENABLED = bool(_ARMED)


def arm(site, action):
    """Arm one site; `action` is an action spec string (``error``,
    ``error:2``, ``delay:10``, ``kill``)."""
    if site not in SITES:
        raise ValueError(f"unknown failpoint site {site!r}; known sites: "
                         f"{', '.join(sorted(SITES))}")
    with _LOCK:
        _ARMED[site] = _parse_action(site, action)
        _refresh_enabled()


def disarm(site):
    with _LOCK:
        _ARMED.pop(site, None)
        _refresh_enabled()


def reset():
    """Disarm every site and zero the hit counters."""
    with _LOCK:
        _ARMED.clear()
        _HITS.clear()
        _refresh_enabled()


def armed():
    """{site: action-spec-string} for currently armed sites."""
    with _LOCK:
        return {s: a.spec() for s, a in _ARMED.items()}


def hits(site):
    """How many times `site` has fired since the last reset()."""
    with _LOCK:
        return _HITS.get(site, 0)


def is_enabled():
    return _ENABLED


def arm_from_flag():
    """(Re-)apply the FLAGS_failpoints spec — call after paddle.set_flags
    changes the flag at runtime (import-time env values apply
    automatically)."""
    spec = _flags.get_flag("failpoints", "") or ""
    actions = parse(spec)
    with _LOCK:
        _ARMED.clear()
        _ARMED.update(actions)
        _refresh_enabled()


@contextlib.contextmanager
def scoped(spec):
    """Arm a spec for the with-block, restoring the previous arming (and
    enabled state) on exit — the test-side entry point::

        with failpoints.scoped("serving/slot=error:1"):
            engine.step()
    """
    actions = parse(spec)
    with _LOCK:
        saved = dict(_ARMED)
        _ARMED.update(actions)
        _refresh_enabled()
    try:
        yield
    finally:
        with _LOCK:
            _ARMED.clear()
            _ARMED.update(saved)
            _refresh_enabled()


def _note_fire(site, kind):
    global _TRIG
    if not _monitor.is_enabled():
        return
    if _TRIG is None:
        _TRIG = _monitor.counter(
            "failpoint_trigger_total",
            "armed failpoint fires by site and action (always zero in "
            "production: the series only exists once a fault is injected)",
            labelnames=("site", "action"))
    _TRIG.labels(site=site, action=kind).inc()


def failpoint(site):
    """The planted call. Disabled (nothing armed anywhere): one boolean
    check and return — the fast path tests/test_failpoints_gate.py pins."""
    if not _ENABLED:
        return
    _fire(site)


def transform(site, value):
    """Value-transforming failpoint: plant where data flows through a
    site. Disabled (nothing armed anywhere): one boolean check, `value`
    returned untouched. A ``scale:F`` action multiplies every FLOAT
    array in `value` (a single array, or a list/tuple of them) by F —
    ``scale:nan`` poisons them into a non-finite step — while integer
    arrays pass through unchanged; any other armed action behaves
    exactly as :func:`failpoint` (error raises, delay sleeps) before
    `value` is returned."""
    if not _ENABLED:
        return value
    with _LOCK:
        act = _ARMED.get(site)
        scale = act is not None and act.kind == "scale"
        if scale:
            _HITS[site] = _HITS.get(site, 0) + 1
            factor = act.arg
    if not scale:
        _fire(site)
        return value
    _note_fire(site, "scale")
    import numpy as _np

    def _scaled(a):
        dt = getattr(a, "dtype", None)
        if dt is None or not _np.issubdtype(_np.dtype(dt), _np.floating):
            return a
        return a * _np.asarray(factor).astype(dt)

    if isinstance(value, (list, tuple)):
        return type(value)(_scaled(a) for a in value)
    return _scaled(value)


def _fire(site):
    with _LOCK:
        act = _ARMED.get(site)
        if act is None or act.kind == "scale":
            # scale actions only act through transform(); a plain
            # failpoint() at the same site must not consume or crash
            return
        if act.remaining is not None and act.remaining <= 0:
            # an exhausted error:N re-armed by scoped()'s restore (the
            # _Action is shared, its budget already spent) — disarm, don't
            # fire an N+1th time
            del _ARMED[site]
            _refresh_enabled()
            return
        _HITS[site] = _HITS.get(site, 0) + 1
        if act.remaining is not None:
            act.remaining -= 1
            if act.remaining <= 0:
                del _ARMED[site]
                _refresh_enabled()
        kind = act.kind
        delay_ms = act.arg
    _note_fire(site, kind)
    if kind == "error":
        raise FailpointError(f"failpoint {site!r}: injected error")
    if kind == "delay":
        time.sleep(delay_ms / 1e3)
        return
    if kind == "kill":   # crash-mid-operation: no cleanup handlers run
        os.kill(os.getpid(), signal.SIGKILL)


# import-time arming from the environment (FLAGS_failpoints=...)
if _flags.get_flag("failpoints", ""):
    arm_from_flag()
