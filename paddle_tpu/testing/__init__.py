"""Test-support machinery that ships with the framework.

`failpoints` is the registered fault-injection framework (docs/ROBUSTNESS.md):
sites planted in the runtime's recovery-critical paths (checkpoint write/read,
executor compile, collectives, the serving step loop) that are a single
boolean check when disabled and inject errors/delays/kills when armed via
``FLAGS_failpoints`` or ``failpoints.scoped(...)``.
"""
from . import failpoints  # noqa: F401
from .failpoints import FailpointError, failpoint  # noqa: F401

__all__ = ["failpoints", "failpoint", "FailpointError"]
