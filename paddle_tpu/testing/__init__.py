"""Test-support machinery that ships with the framework.

`failpoints` is the registered fault-injection framework (docs/ROBUSTNESS.md):
sites planted in the runtime's recovery-critical paths (checkpoint write/read,
executor compile, collectives, the serving step loop) that are a single
boolean check when disabled and inject errors/delays/kills when armed via
``FLAGS_failpoints`` or ``failpoints.scoped(...)``.

`parity` is the lockstep A/B loss-parity harness (docs/OBSERVABILITY.md
"Numerics telescope"): two trainers over identical batches under a
reference vs candidate flag-set, per-step loss + grad-stat divergence
asserted within declared tolerances. Loaded lazily — importing the
failpoint framework must not pull the numerics telescope along.
"""
from . import failpoints  # noqa: F401
from .failpoints import FailpointError, failpoint  # noqa: F401

__all__ = ["failpoints", "failpoint", "FailpointError"]


def __getattr__(name):   # PEP 562: lazy parity-harness loading — NOT in
    # __all__ (a star-import would resolve it and defeat the laziness)
    if name == "parity":
        import importlib

        return importlib.import_module(".parity", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
