"""paddle.fft namespace (python/paddle/fft.py parity) — thin jnp.fft wrappers."""
import jax.numpy as jnp

from .core.dispatch import apply
from .core.tensor import Tensor


def _t(x):
    import numpy as np

    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _mk(name):
    jfn = getattr(jnp.fft, name)

    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply(lambda v: jfn(v, n=n, axis=axis, norm=norm), _t(x))

    op.__name__ = name
    return op


def _mk_nd(name):
    jfn = getattr(jnp.fft, name)

    def op(x, s=None, axes=None, norm="backward", name_=None):
        return apply(lambda v: jfn(v, s=s, axes=axes, norm=norm), _t(x))

    op.__name__ = name
    return op


fft = _mk("fft")
ifft = _mk("ifft")
rfft = _mk("rfft")
irfft = _mk("irfft")
hfft = _mk("hfft")
ihfft = _mk("ihfft")
fft2 = _mk_nd("fft2")
ifft2 = _mk_nd("ifft2")
rfft2 = _mk_nd("rfft2")
irfft2 = _mk_nd("irfft2")
fftn = _mk_nd("fftn")
ifftn = _mk_nd("ifftn")
rfftn = _mk_nd("rfftn")
irfftn = _mk_nd("irfftn")


def fftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.fftshift(v, axes=axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.ifftshift(v, axes=axes), _t(x))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d))
