"""Global flag registry.

Reference parity: paddle/fluid/platform/flags.cc (~40 process-level gflags, exposed to
Python as FLAGS_* via pybind/global_value_getter_setter.cc) and
paddle.set_flags/get_flags. Flags can be seeded from environment (FLAGS_xxx=...).
"""
import os

_REGISTRY = {}


def define_flag(name, default, help_str=""):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    existing = _REGISTRY.get(name)
    if existing is not None:
        # Two real definitions disagreeing about the default is a bug:
        # whichever module imported first silently won (and its env
        # parsing keyed off ITS default's type). Raise instead — the
        # idempotent same-default path stays allowed, and entries a
        # set_flags() created before the defining module loaded
        # ("provisional": the user picked a value, never a default) are
        # adopted, not conflicted with.
        if not existing.get("provisional") \
                and repr(existing["default"]) != repr(default):
            raise ValueError(
                f"FLAGS_{name} re-defined with default {default!r} but "
                f"an earlier define_flag said {existing['default']!r} — "
                "conflicting defaults would be resolved by import order; "
                "one definition must own the default")
        # an explicit set_flags() made BEFORE the defining module loaded
        # wins: lazily-imported modules (monitor/numerics.py) define
        # their flags on first import, and defining must never clobber a
        # value the user already set
        value = existing["value"]
        if not help_str:
            help_str = existing["help"]
    _REGISTRY[name] = {"value": value, "default": default, "help": help_str}
    return value


def set_flags(flags):
    """paddle.set_flags parity."""
    for k, v in flags.items():
        k = k[6:] if k.startswith("FLAGS_") else k
        if k not in _REGISTRY:
            # provisional entry, NOT define_flag: an explicit set wins
            # over any FLAGS_* env var (exactly as it does for an
            # already-defined flag), and the defining module may load
            # later with the authoritative default + help (see
            # define_flag's provisional adoption)
            _REGISTRY[k] = {"value": v, "default": v, "help": "",
                            "provisional": True}
        else:
            _REGISTRY[k]["value"] = v


def get_flags(names):
    """paddle.get_flags parity."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for k in names:
        key = k[6:] if k.startswith("FLAGS_") else k
        if key in _REGISTRY:
            out[k] = _REGISTRY[key]["value"]
    return out


def get_flag(name, default=None):
    e = _REGISTRY.get(name)
    return e["value"] if e else default


# core flags (platform/flags.cc parity where meaningful on TPU)
define_flag("check_nan_inf", False,
            "scan op outputs for NaN/Inf (flags.cc:44); SpmdTrainer builds "
            "its step with an on-device loss/grad finiteness check and "
            "SKIPS the update on a non-finite step (docs/ROBUSTNESS.md)")
define_flag("max_skip_steps", 3,
            "with FLAGS_check_nan_inf: how many CONSECUTIVE non-finite "
            "train steps may be skipped before train_step raises "
            "FloatingPointError (a transient loss spike recovers; a "
            "diverged run fails loudly)")
define_flag("sort_sum_gradient", False,  # lint: allow(orphan-flag) — reference-parity stub (flags.cc:527): tape accumulation is already deterministic here, kept for set_flags API compat
            "deterministic grad accumulation order (flags.cc:527); the "
            "TPU tape accumulates in recording order deterministically, "
            "so this is accepted-and-ignored for API compatibility")
define_flag("benchmark", False,
            "Executor.run blocks until fetches are device-complete so the "
            "monitor's step_latency_ms measures device work, not dispatch; "
            "each sync is counted as benchmark_sync_total")
define_flag("seed", 0,
            "initial global random seed: seeds the default RNG generator "
            "at process start (core/generator.py); paddle.seed() "
            "overrides it at runtime")
define_flag("use_bfloat16", True, "prefer bfloat16 matmuls on MXU")
define_flag("trace_host_sync", "silent",
            "what Tensor._to_host does when a host pull (.numpy()/.item()) "
            "happens inside a jax trace: silent (jax's own tracer error), "
            "warn (explain the sync first), error (raise immediately). "
            "The analysis host-sync pass polices the compiled-in form.")
define_flag("numerics", False,
            "numerics telescope (monitor/numerics.py): SpmdTrainer builds "
            "its step with ONE fused on-device per-layer tensor-health "
            "aggregation (grad/param norms, update ratio, non-finite "
            "counts, quantile digest) feeding drift detectors; unset, the "
            "train step is bit-identical to the un-instrumented one. "
            "Defined here (not in the numerics module) so the trainer can "
            "gate on it without importing the telescope at all")
define_flag("numerics_interval", 1,
            "with FLAGS_numerics: fetch the on-device stats to the host "
            "every N train steps (the stats stay device-resident between "
            "fetches — no new per-step host sync)")
define_flag("quantized_allreduce", False,
            "EQuARX-style quantized gradient all-reduce "
            "(distributed/compress.py, docs/DISTRIBUTED.md): on the "
            "plain-dp SpmdTrainer path the per-step grad psum becomes an "
            "int8-wire reduce (stochastic rounding, fp32 accumulation) "
            "with per-layer error-feedback residuals riding the "
            "optimizer-state pytree. Read at TRAINER CONSTRUCTION (the "
            "residual state is laid out then) — changing it under a live "
            "trainer raises instead of silently mis-reducing. localsgd/"
            "DGC steps ignore it (they own their reduce), like the "
            "FLAGS_check_nan_inf carve-out. Unset, the trainer never "
            "imports the compress module and the step is byte-identical")
define_flag("quantized_allreduce_bits", 8,
            "wire width of the quantized all-reduce payload; 8 (int8) is "
            "the supported format — anything else fails loudly at "
            "trainer construction. Read at trainer construction")
define_flag("quantized_allreduce_min_size", 1024,
            "with FLAGS_quantized_allreduce: tensors smaller than this "
            "many elements (and all non-float gradients) skip "
            "quantization and stay on the exact fp32 reduce — the scale "
            "overhead and risk aren't worth <4KB of wire. Read at "
            "trainer construction")
define_flag("shard_weight_update", False,
            "arXiv:2004.13336-style cross-replica update sharding for "
            "plain dp (docs/DISTRIBUTED.md): reduce-scatter the grads, "
            "compute the optimizer update on each replica's 1/dp shard "
            "(optimizer moments stored sharded — ZeRO-2-like memory), "
            "all-gather the updated params; bit-compared EXACT against "
            "the replicated update by tools/parity_check.py. Composes "
            "with FLAGS_quantized_allreduce (the quantized exchange "
            "feeds the sharded update). Read at trainer construction; "
            "localsgd/DGC ignore it")
define_flag("async_dispatch", False,
            "double-buffered step dispatch (docs/PERF.md): SpmdTrainer "
            "returns a lazy StepHandle (distributed/async_dispatch.py), "
            "the non-finite guard verdict is fetched in windows of "
            "FLAGS_async_window steps instead of per step, and "
            "ServingEngine.step overlaps admission/bookkeeping for the "
            "next round with the current round's device compute. Read at "
            "TRAINER/ENGINE CONSTRUCTION — a post-construction toggle "
            "under a live trainer raises. Unset, the async module is "
            "never imported and behavior is byte-identical")
define_flag("async_window", 8,
            "with FLAGS_async_dispatch: how many steps the host may run "
            "ahead of the deferred non-finite-guard verdict fetch (the "
            "FLAGS_max_skip_steps/FloatingPointError contract holds — "
            "the host just learns about an on-device skip up to this "
            "many steps later). 1 = fetch every step (the non-async "
            "deferred-by-one behavior). Read at trainer construction")
define_flag("overlap_grad_comm", False,
            "with FLAGS_quantized_allreduce (quant-only mode): split the "
            "fused int8 gradient exchange into per-layer legs so XLA's "
            "scheduler can interleave the collective legs with backward "
            "compute (EQuARX hides the quantized exchange behind "
            "compute; docs/PERF.md overlap matrix). Changes the rounding "
            "rng per leg — parity-banded vs the fused bundle. Read at "
            "trainer construction; raises without quantized_allreduce "
            "or combined with shard_weight_update (already per-leg)")
define_flag("tpp_kernels", False,
            "TPP-style Pallas micro-kernel registry (ops/tpp.py, "
            "arXiv:2104.05755): GPT blocks route their fusion-hostile "
            "hot ops — the fused MLP block and the layernorm->matmul "
            "prologue — through blocked Pallas kernels (interpret-mode "
            "on CPU). Read at trace time in models/gpt.py; unset, the "
            "registry module is never imported and the traced program "
            "is byte-identical")
define_flag("mpmd", False,
            "MPMD stage-program runtime (distributed/stage.py, "
            "arXiv:2412.14374): PipelineTrainer schedules its stages as "
            "per-stage AOT-cached programs on their own mesh slices "
            "connected by typed, backpressured transfer edges (1F1B / "
            "F-then-B / interleaved tick orderings over the same edges), "
            "and DisaggregatedPool routes its prefill->decode hand-off "
            "over the same edge abstraction (compress=8 rides the "
            "EQuARX int8 row codec). Read at TRAINER/POOL CONSTRUCTION "
            "— a post-construction toggle under a live trainer raises. "
            "Unset, distributed/stage.py is never imported "
            "(manifest-lazy; analysis/import_graph.py) and behavior is "
            "byte-identical")
define_flag("paged_kv", False,
            "paged KV-cache + batched multi-LoRA serving "
            "(serving/paging.py, arXiv:2309.06180 recipe): ServingEngine "
            "replaces its dense [max_batch, max_seq] KV cache with a "
            "physical block pool + per-slot block tables — whole-budget "
            "reservation at admission (PagePoolFullError backpressure "
            "BEFORE any prefill compute), refcounted shared-prefix "
            "frames with copy-on-write boundary blocks, int8 cold-page "
            "compression (page_cold_steps=, EQuARX row codec), and "
            "named-adapter decode (load_adapter/submit(adapter=)) "
            "batched in the ONE jitted step via a gathered low-rank "
            "delta — no per-adapter programs, no recompiles. Read at "
            "ENGINE CONSTRUCTION — a post-construction toggle under a "
            "live paged engine raises; the boolean joins the serving AOT "
            "extra_key so paged executables never alias dense ones. "
            "Unset, serving/paging.py is never imported (manifest-lazy; "
            "analysis/import_graph.py) and the engine is byte-identical")
define_flag("blackbox", False,
            "black-box flight recorder on/off (monitor/blackbox.py): "
            "progress beacons, the bounded event ring, and dump-bundle "
            "plumbing; off turns every beacon()/note() call site into "
            "one boolean check (tests/test_blackbox_gate.py pins "
            "<5us/call and zero drift). Defined here (not in the "
            "recorder module) so the monitor package can gate on it "
            "without importing the recorder at all — monitor/blackbox.py "
            "is manifest-lazy (analysis/import_graph.py)")
define_flag("flash_attention_block", 0,
            "force the flash-attention Pallas block size (128/256/512); "
            "0 = auto (largest of 512/256/128 dividing seq). For on-chip "
            "tuning sweeps: FLAGS_flash_attention_block=256 python bench.py")
define_flag("perf_ledger", False,
            "persistent perf ledger (monitor/perfledger.py, "
            "docs/OBSERVABILITY.md): trainer/engine/stage-graph/bench "
            "step telemetry (wall ms, MFU, collective bytes, dispatch "
            "fraction, latency digests) is appended as env-fingerprinted "
            "JSONL rows to FLAGS_perf_ledger_path, with an EMA/sigma "
            "regression sentinel firing perf_regression_total{site,"
            "metric}. DELIBERATELY NON-STRUCTURAL: the ledger only "
            "observes host-side timings and never changes any compiled "
            "program, so it does NOT join the executable keys (armed and "
            "disarmed runs share AOT cache entries and train "
            "byte-identically — tests/test_perfledger_gate.py pins it). "
            "Unset, the ledger module is never imported and every hook "
            "is one boolean check. Defined here (not in the ledger "
            "module) so trainers can gate on it without importing it")
define_flag("perf_ledger_path", "",
            "with FLAGS_perf_ledger: path of the append-only JSONL "
            "ledger file. Appends are atomic (single write+flush+fsync "
            "per row) and readers tolerate a torn tail, like bench.py "
            "--banked. Empty = rows are kept in-process only (sentinel "
            "and metrics still run; nothing persists)")
define_flag("perf_ledger_sigma", 4.0,
            "with FLAGS_perf_ledger: regression threshold — a step "
            "metric more than this many EMA standard deviations on the "
            "bad side of its per-(site,metric) baseline fires "
            "perf_regression_total and notes the blackbox ring")
define_flag("perf_ledger_warmup", 5,
            "with FLAGS_perf_ledger: observations of a (site,metric) "
            "series before the sentinel may fire (the EMA baseline "
            "needs points; the NumericsMonitor warmup contract)")
define_flag("perf_ledger_interval", 1,
            "with FLAGS_perf_ledger: append a ledger row every N "
            "observations per site (the sentinel still sees every "
            "observation; only row volume is throttled)")
define_flag("elastic", False,
            "elastic preemption-tolerant training "
            "(distributed/elastic.py supervisor + the spmd.py "
            "topology-aware checkpoint reshard, arXiv:2412.14374 "
            "posture): gather_train_state stamps logical [param, "
            "shard-spec] metadata into every checkpoint so "
            "restore_train_state re-lays-out [dp, shard] moments and "
            "__qar_residual__ EF residuals onto a DIFFERENT dp/mp "
            "factorization (checkpoint_reshard_total{action}), "
            "SpmdTrainer.resize(mesh) drains and re-places live state "
            "onto a replacement mesh through the AOT disk cache, "
            "StageProgram.rebind/MpmdPipelineRunner.replace_stage swap "
            "one MPMD stage mesh without recompiling siblings, and "
            "ElasticSupervisor wires CheckpointSaver corrupt-fallback + "
            "blackbox crash bundles into retry-with-backoff resume on a "
            "shrunken mesh (elastic_resume_total{reason}). Read at "
            "TRAINER CONSTRUCTION — a post-construction toggle under a "
            "live trainer raises (_elastic_active). STRUCTURAL: the "
            "boolean joins _exec_key and the AOT extra_key so an "
            "elastic world never aliases a plain executable. Unset, "
            "distributed/elastic.py is never imported (manifest-lazy; "
            "analysis/import_graph.py) and training is byte-identical")
define_flag("goodput", False,
            "goodput ledger + weight-version lineage metrics "
            "(monitor/goodput.py, docs/OBSERVABILITY.md): a per-run "
            "wall-clock accountant classifies every second into "
            "exclusive buckets {step, compile, ckpt_save, ckpt_restore, "
            "reshard, resume_backoff, stall, edge_wait, other} via hooks "
            "in the trainer/AOT path, checkpoint save/restore, the "
            "elastic supervisor, and the MPMD stage runtime — published "
            "as goodput_seconds_total{bucket} + goodput_fraction, one "
            "site=run/goodput perf-ledger row per run (FLAGS_perf_ledger "
            "also armed; goodput itself is sentinel-watched LOW_IS_BAD), "
            "and a blackbox dump provider naming the active bucket at "
            "crash time. Also gates the serving lineage families "
            "(serving_weight_version / serving_stale_sessions_total). "
            "DELIBERATELY NON-STRUCTURAL: host-side accounting only — "
            "it joins NO executable key (armed and disarmed runs share "
            "AOT entries and train byte-identically — "
            "tests/test_goodput_gate.py pins it). Unset, "
            "monitor/goodput.py is never imported and every hook is one "
            "cached boolean. Defined here (not in the accountant module) "
            "so hook sites can gate on it without importing it")
define_flag("goodput_stall_s", 2.0,
            "with FLAGS_goodput: an unattributed gap (no bucket active) "
            "at least this many seconds books as `stall`; shorter gaps "
            "book as `other` (loop/bookkeeping overhead)")
