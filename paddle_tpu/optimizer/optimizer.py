"""Optimizer base + the full paddle optimizer family.

Reference parity: python/paddle/optimizer/optimizer.py (Optimizer.step/minimize/
clear_grad), operators/optimizers/{sgd,momentum,adam,adamw,lamb,lars_momentum,rmsprop,
adagrad,adadelta,adamax,ftrl}_op.cc update rules (the C++ kernels' exact math, fused
here into single jitted XLA updates).

TPU-native design: every optimizer defines a pure `_rule(p, g, state, hp) -> (p, state)`.
Eager `step()` runs it under one jit per param-group; the same rule powers the functional
train-step used by Model.fit-static / fleet (optax-style, but paddle semantics).
"""
import jax
import jax.numpy as jnp

from ..core.tensor import ParamBase, Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else []
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._wd = 0.0
            self._wd_is_l2 = True
        elif isinstance(weight_decay, (int, float)):
            self._wd = float(weight_decay)
            self._wd_is_l2 = True
        else:  # L2Decay/L1Decay object
            self._wd = float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))
            self._wd_is_l2 = weight_decay.__class__.__name__ != "L1Decay"
        self._state = {}  # id(param) -> dict of jnp arrays
        self._step_count = 0
        self._jit_rule = jax.jit(self._rule_with_decay)

    # -- learning rate ---------------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # -- state -----------------------------------------------------------------
    def _get_state(self, p):
        k = id(p)
        if k not in self._state:
            self._state[k] = self._init_state(p)
        return self._state[k]

    def _init_state(self, p):
        return {}

    # -- update rule (pure; overridden per optimizer) --------------------------
    def _rule(self, p, g, state, lr):
        raise NotImplementedError

    def _rule_with_decay(self, p, g, state, lr, wd):
        # L2 regularization folded into grad (paddle regularizer semantics);
        # decoupled decay (AdamW) overrides this.
        if self._wd_is_l2:
            g = g + wd * p
        else:
            g = g + wd * jnp.sign(p)
        return self._rule(p, g, state, lr)

    # -- public API ------------------------------------------------------------
    def step(self):
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._parameters if p.grad is not None and getattr(p, "trainable", True)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = jnp.asarray(self.get_lr(), dtype=jnp.float32)
        wd = jnp.asarray(self._wd, dtype=jnp.float32)
        for p, g in params_grads:
            if g is None:
                continue
            state = self._get_state(p)
            new_p, new_state = self._jit_rule(p._data, g._data.astype(p._data.dtype), state, lr, wd)
            p._data = new_p
            self._state[id(p)] = new_state

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from .. import static as _static

        if _static.in_static_mode():
            # static world: attach the optimizer to the recorded program
            # (append_backward + optimize-op insertion, executor-side).
            # set_optimizer raises if the loss is not a var of the program —
            # a silent eager fallback would train against zero placeholders.
            prog = _static.default_main_program()
            prog.set_optimizer(self, loss, parameters=parameters,
                               no_grad_set=no_grad_set)
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameters]

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad

    def state_dict(self):
        out = {"LR_Scheduler": self._lr.state_dict() if isinstance(self._lr, LRScheduler) else {}}
        for i, p in enumerate(self._parameters):
            st = self._state.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{p.name or i}_{k}"] = Tensor(v)
        out["step"] = self._step_count
        return out

    def set_state_dict(self, state):
        if isinstance(self._lr, LRScheduler) and state.get("LR_Scheduler"):
            self._lr.set_state_dict(state["LR_Scheduler"])
        self._step_count = state.get("step", 0)
        for i, p in enumerate(self._parameters):
            st = self._init_state(p)
            loaded = {}
            for k in st:
                key = f"{p.name or i}_{k}"
                if key in state:
                    v = state[key]
                    loaded[k] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            if loaded:
                st.update(loaded)
                self._state[id(p)] = st

    # -- functional view (for jitted/sharded train steps) ----------------------
    def functional_init(self, params):
        """params: dict name->array. Returns state pytree."""
        states = {}
        for n, v in params.items():
            fake = Tensor(v)
            states[n] = self._init_state(fake)
        states["__step__"] = jnp.zeros((), jnp.int32)
        return states

    def functional_apply(self, params, grads, states, lr=None):
        """Pure update over dicts of arrays. Returns (new_params, new_states).

        `lr` may be passed as a traced array so LR schedules work under jit."""
        lr = jnp.asarray(self.get_lr(), dtype=jnp.float32) if lr is None else jnp.asarray(lr, dtype=jnp.float32)
        wd = jnp.asarray(self._wd, dtype=jnp.float32)
        new_params, new_states = {}, {}
        if self._grad_clip is not None and isinstance(self._grad_clip, _GLOBAL_NORM_TYPES):
            clip_norm = self._grad_clip.clip_norm
            sq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in grads.values())
            gnorm = jnp.sqrt(sq)
            scale = clip_norm / jnp.maximum(gnorm, clip_norm)
            grads = {k: (g * scale).astype(g.dtype) for k, g in grads.items()}
        for n, p in params.items():
            g = grads[n]
            st = {k: v for k, v in states[n].items()}
            new_p, new_st = self._rule_with_decay(p, g.astype(p.dtype), st, lr, wd)
            new_params[n] = new_p
            new_states[n] = new_st
        new_states["__step__"] = states["__step__"] + 1
        return new_params, new_states


from ..nn.clip import ClipGradByGlobalNorm as _CGBGN  # noqa: E402

_GLOBAL_NORM_TYPES = (_CGBGN,)


class SGD(Optimizer):
    """operators/optimizers/sgd_op.cc parity."""

    def _rule(self, p, g, state, lr):
        return p - lr.astype(p.dtype) * g, state


class Momentum(Optimizer):
    """operators/optimizers/momentum_op.cc parity (incl. nesterov)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        self._momentum = momentum
        self._nesterov = use_nesterov
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._data)}

    def _rule(self, p, g, state, lr):
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_p = p - lr.astype(p.dtype) * (g + self._momentum * v)
        else:
            new_p = p - lr.astype(p.dtype) * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """operators/optimizers/adam_op.cc parity (bias-corrected via beta-pow state)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._data),
            "moment2": jnp.zeros_like(p._data),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _rule(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * (g * g)
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        new_p = p - (lr_t.astype(p.dtype) * m / (jnp.sqrt(v) + eps)).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """python/paddle/optimizer/adamw.py parity — decoupled weight decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        self._decay_fun = apply_decay_param_fun
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip)

    def _rule_with_decay(self, p, g, state, lr, wd):
        # decoupled: p -= lr*wd*p before adam update (paddle adamw semantics)
        p = p * (1.0 - lr.astype(p.dtype) * wd.astype(p.dtype))
        return self._rule(p, g, state, lr)


class Adagrad(Optimizer):
    """operators/optimizers/adagrad_op.cc parity."""

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        self._eps = epsilon
        self._init_acc = initial_accumulator_value
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data, self._init_acc)}

    def _rule(self, p, g, state, lr):
        mom = state["moment"] + g * g
        new_p = p - lr.astype(p.dtype) * g / (jnp.sqrt(mom) + self._eps)
        return new_p, {"moment": mom}


class Adadelta(Optimizer):
    """operators/optimizers/adadelta_op.cc parity."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        self._eps = epsilon
        self._rho = rho
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._data),
                "avg_squared_update": jnp.zeros_like(p._data)}

    def _rule(self, p, g, state, lr):
        rho, eps = self._rho, self._eps
        asg = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        update = -jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps) * g
        asu = rho * state["avg_squared_update"] + (1 - rho) * update * update
        return p + lr.astype(p.dtype) * update, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    """operators/optimizers/adamax_op.cc parity."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p._data),
                "inf_norm": jnp.zeros_like(p._data),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _rule(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state["beta1_pow"] * b1
        m = b1 * state["moment"] + (1 - b1) * g
        inf = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g) + eps)
        new_p = p - (lr / (1 - b1p)).astype(p.dtype) * m / inf
        return new_p, {"moment": m, "inf_norm": inf, "beta1_pow": b1p}


class RMSProp(Optimizer):
    """operators/optimizers/rmsprop_op.cc parity (centered option)."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._data), "moment": jnp.zeros_like(p._data)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._data)
        return st

    def _rule(self, p, g, state, lr):
        rho, eps = self._rho, self._eps
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state["moment"] + lr.astype(p.dtype) * g / denom
        new_state = {"mean_square": ms, "moment": mom}
        if self._centered:
            new_state["mean_grad"] = mg
        return p - mom, new_state


class Lamb(Optimizer):
    """operators/optimizers/lamb_op.cc parity (trust-ratio layerwise adaptation)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        super().__init__(learning_rate, parameters, None, grad_clip)

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._data),
            "moment2": jnp.zeros_like(p._data),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _rule(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
        r_norm = jnp.sqrt(jnp.sum(r.astype(jnp.float32) ** 2))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p - (lr * ratio).astype(p.dtype) * r
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class Lars(Momentum):
    """operators/optimizers/lars_momentum_op.cc parity."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None):
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._lars_eps = epsilon
        super().__init__(learning_rate, momentum, parameters, False, None, grad_clip)

    def _rule(self, p, g, state, lr):
        p_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
        g_norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm / (g_norm + self._lars_wd * p_norm + self._lars_eps),
            1.0,
        )
        v = self._momentum * state["velocity"] + (lr * local_lr).astype(p.dtype) * (g + self._lars_wd * p)
        return p - v, {"velocity": v}


LarsMomentum = Lars


class Ftrl(Optimizer):
    """operators/optimizers/ftrl_op.cc parity."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        self._l1, self._l2, self._lr_power = l1, l2, lr_power
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def _init_state(self, p):
        return {"squared": jnp.zeros_like(p._data), "linear": jnp.zeros_like(p._data)}

    def _rule(self, p, g, state, lr):
        l1, l2, lrp = self._l1, self._l2, self._lr_power
        new_sq = state["squared"] + g * g
        sigma = (new_sq ** -lrp - state["squared"] ** -lrp) / lr.astype(p.dtype)
        lin = state["linear"] + g - sigma * p
        pre = jnp.where(jnp.abs(lin) > l1, (jnp.sign(lin) * l1 - lin) /
                        (new_sq ** -lrp / lr.astype(p.dtype) + 2 * l2), jnp.zeros_like(p))
        return pre, {"squared": new_sq, "linear": lin}


class Dpsgd(SGD):
    """operators/optimizers/dpsgd_op.cc (differentially-private SGD) — clip+noise."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16, sigma=1.0,
                 parameters=None, name=None):
        self._clip_v = clip
        self._sigma = sigma
        self._batch = batch_size
        super().__init__(learning_rate, parameters)
        self._jit_rule = self._rule_with_decay  # fresh noise per step: stay un-jitted

    def _rule(self, p, g, state, lr):
        from ..core.generator import default_generator

        gnorm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        g = g / jnp.maximum(1.0, gnorm / self._clip_v)
        key = default_generator().split()
        noise = jax.random.normal(key, g.shape, dtype=g.dtype) * (self._sigma * self._clip_v / self._batch)
        return p - lr.astype(p.dtype) * (g + noise), state
