"""Averaging/wrapping optimizers: ExponentialMovingAverage, ModelAverage, LookAhead.

Reference parity: python/paddle/fluid/optimizer.py (ModelAverage:3157,
ExponentialMovingAverage:3466) and the LookAhead optimizer from
python/paddle/fluid/incubate (SURVEY.md §Appendix A optimizer extras). TPU-native
design: these keep shadow copies of parameters as host-resident jnp arrays and
swap them in/out of the live Layer parameters — no graph rewriting needed, since
eager Tensors rebind `_data` functionally.
"""
import contextlib

import jax.numpy as jnp

from .optimizer import Optimizer


class ExponentialMovingAverage:
    """shadow = decay * shadow + (1 - decay) * param, with bias correction
    matching fluid/optimizer.py:3466 (thres_steps-free form)."""

    def __init__(self, parameters, decay=0.999, name=None):
        self._decay = float(decay)
        self._parameters = list(parameters)
        # shadow starts at 0 so the (1 - decay^t) bias correction in apply()
        # is exact, matching the reference's ema_0 = 0 accumulation scheme
        self._shadow = {id(p): jnp.zeros_like(p._data) for p in self._parameters}
        self._step = 0
        self._backup = None

    def update(self):
        self._step += 1
        d = self._decay
        for p in self._parameters:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1.0 - d) * p._data

    @contextlib.contextmanager
    def apply(self, need_restore=True):
        """Swap EMA weights in (bias-corrected); restore originals on exit."""
        self._backup = {id(p): p._data for p in self._parameters}
        corr = 1.0 - self._decay ** max(self._step, 1)
        for p in self._parameters:
            p._data = (self._shadow[id(p)] / corr).astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._backup is None:
            return
        for p in self._parameters:
            p._data = self._backup[id(p)]
        self._backup = None


class ModelAverage:
    """Running average of parameters over a sliding window
    (fluid/optimizer.py:3157). `update()` per step; `apply()` swaps the
    averaged weights in for evaluation."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._parameters = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._parameters}
        self._num = 0
        self._backup = None

    def update(self):
        # Window restarts once it outgrows max(min_window, rate * steps) — the
        # same sliding-window intent as the reference's sum_1/2/3 rotation.
        window = max(self._min_w, min(self._max_w, int(self._rate * (self._num + 1)) or 1))
        if self._num >= window:
            self._num = 0
            for p in self._parameters:
                self._sum[id(p)] = jnp.zeros_like(p._data)
        self._num += 1
        for p in self._parameters:
            self._sum[id(p)] = self._sum[id(p)] + p._data

    @contextlib.contextmanager
    def apply(self, need_restore=True):
        self._backup = {id(p): p._data for p in self._parameters}
        n = max(self._num, 1)
        for p in self._parameters:
            p._data = (self._sum[id(p)] / n).astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._backup is None:
            return
        for p in self._parameters:
            p._data = self._backup[id(p)]
        self._backup = None


class LookAhead(Optimizer):
    """k-step lookahead wrapper: every k inner steps, slow weights move
    alpha of the way toward the fast weights and the fast weights reset to
    the slow ones (incubate LookaheadOptimizer parity)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self._alpha = float(alpha)
        self._k = int(k)
        self._parameters = inner_optimizer._parameters
        self._slow = {id(p): jnp.asarray(p._data) for p in self._parameters}
        self._count = 0

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, value):
        self.inner_optimizer.set_lr(value)

    def set_lr_scheduler(self, scheduler):
        self.inner_optimizer.set_lr_scheduler(scheduler)

    @property
    def _learning_rate(self):
        return self.inner_optimizer._learning_rate

    def step(self):
        self.inner_optimizer.step()
        self._count += 1
        if self._count % self._k == 0:
            a = self._alpha
            for p in self._parameters:
                slow = self._slow[id(p)] + a * (p._data - self._slow[id(p)])
                self._slow[id(p)] = slow
                p._data = slow.astype(p._data.dtype)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameters]

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state)
