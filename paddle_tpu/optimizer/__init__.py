"""paddle.optimizer parity surface (python/paddle/optimizer/__init__.py)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Dpsgd,
    Ftrl,
    Lamb,
    Lars,
    LarsMomentum,
    Momentum,
    Optimizer,
    RMSProp,
    SGD,
)
from .extras import (  # noqa: F401
    ExponentialMovingAverage,
    LookAhead,
    ModelAverage,
)
