"""paddle.linalg namespace (python/paddle/linalg.py parity)."""
from .tensor.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, det, eig, eigh, eigvals, eigvalsh,
    householder_product, inv, lstsq, lu, matrix_norm, matrix_power, matrix_rank,
    multi_dot, norm, pinv, qr, slogdet, solve, svd, triangular_solve, vector_norm,
)
from .tensor.math import matmul  # noqa: F401
