"""Profiler.

Reference parity: paddle/fluid/platform/profiler.{h,cc} (RecordEvent:127,
EnableProfiler/DisableProfiler:210-213, event trees -> Profile proto) +
fluid/profiler.py context manager + tools/timeline.py chrome-trace conversion.

TPU-native design: host events keep the RecordEvent tree in pure python; device-side
capture delegates to jax.profiler (XPlane -> TensorBoard / Perfetto, replacing the CUPTI
DeviceTracer). `export_chrome_tracing` emits chrome://tracing JSON like timeline.py.
"""
import contextlib
import threading
import time

import jax

_LOCAL = threading.local()
_ENABLED = [False]
_EVENTS = []  # (name, start_ns, end_ns, thread_id, depth)
_LOCK = threading.Lock()
# the jax device trace is PROCESS state (one trace per process), so its
# on/off flag must be module state: keeping it in threading.local meant a
# stop_profiler from any thread other than the starter silently leaked
# the running trace (the watchdog/monitor threads are exactly such callers)
_JAX_TRACE = [False]


class RecordEvent:
    """platform/profiler.h:127 RAII RecordEvent parity."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()

    def begin(self):
        if not hasattr(_LOCAL, "depth"):
            _LOCAL.depth = 0
        self._start = time.perf_counter_ns()
        _LOCAL.depth += 1

    def end(self):
        if self._start is None or not _ENABLED[0]:
            if hasattr(_LOCAL, "depth") and _LOCAL.depth > 0:
                _LOCAL.depth -= 1
            return
        end = time.perf_counter_ns()
        _LOCAL.depth -= 1
        with _LOCK:
            _EVENTS.append((self.name, self._start, end, threading.get_ident(), _LOCAL.depth))


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    """EnableProfiler parity; also starts the jax device trace when a log_dir is given."""
    _ENABLED[0] = True
    with _LOCK:
        _EVENTS.clear()
    if log_dir:
        with _LOCK:
            jax.profiler.start_trace(log_dir)
            _JAX_TRACE[0] = True


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    _ENABLED[0] = False
    with _LOCK:
        if _JAX_TRACE[0]:
            jax.profiler.stop_trace()
            _JAX_TRACE[0] = False
    return summary(sorted_key)


def host_events():
    """Snapshot of the recorded host events, sorted by start time —
    (name, start_ns, end_ns, thread_id, depth) tuples. The read is taken
    under _LOCK: concurrent RecordEvent.end appends must never be seen
    half-way (list.append is atomic, but iterating while appending from
    another thread can observe a torn ordering)."""
    with _LOCK:
        evts = list(_EVENTS)
    evts.sort(key=lambda e: e[1])
    return evts


def summary(sorted_key=None):
    agg = {}
    for name, s, e, tid, depth in host_events():
        st = agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        dur = (e - s) / 1e6
        st[0] += 1
        st[1] += dur
        st[2] = min(st[2], dur)
        st[3] = max(st[3], dur)
    rows = [
        {"name": k, "calls": v[0], "total_ms": v[1], "min_ms": v[2], "max_ms": v[3],
         "avg_ms": v[1] / v[0] if v[0] else 0.0}
        for k, v in agg.items()
    ]
    return _sort_rows(rows, sorted_key)


def _sort_rows(rows, sorted_key):
    if sorted_key in ("total", None):
        rows.sort(key=lambda r: -r["total_ms"])
    elif sorted_key == "calls":
        rows.sort(key=lambda r: -r["calls"])
    elif sorted_key in ("avg", "ave"):
        rows.sort(key=lambda r: -r["avg_ms"])
    elif sorted_key == "max":
        rows.sort(key=lambda r: -r["max_ms"])
    elif sorted_key == "min":
        rows.sort(key=lambda r: -r["min_ms"])
    return rows


def export_chrome_tracing(path):
    """tools/timeline.py parity: chrome://tracing JSON. Delegates to the
    merged exporter (paddle_tpu.trace.export_chrome), so host events are
    emitted sorted by start time — nested RecordEvents render as a tree
    from ts/dur ordering instead of unordered same-tier slices — and the
    old API's output gains whatever trace spans / counter samples exist."""
    from .. import trace as _trace

    _trace.export_chrome(path)
    return path


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path="/tmp/profile", log_dir=None):
    """fluid/profiler.py profiler context-manager parity."""
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Profiler:
    """paddle.profiler.Profiler (2.x API shape) — wraps the same machinery."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, log_dir=None):
        self._log_dir = log_dir
        self._rows = None

    def start(self):
        start_profiler(log_dir=self._log_dir)

    def stop(self):
        self._rows = stop_profiler()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()

    def summary(self, sorted_by=None, **kw):
        """Rows from the last stop() (or the live buffer), honoring
        sorted_by ("total"|"calls"|"avg"|"max"|"min") — previously the
        argument was silently ignored."""
        if self._rows is None:
            return summary(sorted_by)
        return _sort_rows(list(self._rows), sorted_by)
