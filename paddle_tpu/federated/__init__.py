"""Federated MapReduce: a ``clients`` axis for the SPMD stack.

DrJAX-style primitives (PAPERS.md, arXiv:2403.07128) — ``client_map``
over a named ``clients`` mesh axis with differentiable ``federated_*``
reduces through the metered collective chokepoint — plus a
``FederatedAverager`` FedAvg/FedSGD loop that composes with
``incubate.lora`` for federated/multi-task fine-tuning. See
docs/FEDERATED.md.

Deliberately NOT imported by ``paddle_tpu/__init__.py``: a deployment
that never federates never pays for (or registers metrics from) this
package — tests/test_federated_gate.py pins that.
"""
from .averaging import FederatedAverager
from .data import partition_clients
from .primitives import (CLIENTS_AXIS, broadcast_to_clients, client_map,
                         federated_mean, federated_sum,
                         federated_weighted_mean, in_client_map,
                         num_clients)

__all__ = [
    "CLIENTS_AXIS", "broadcast_to_clients", "client_map", "federated_sum",
    "federated_mean", "federated_weighted_mean", "in_client_map",
    "num_clients", "partition_clients", "FederatedAverager",
]
