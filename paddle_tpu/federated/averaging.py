"""FedAvg/FedSGD training loop over the MapReduce primitives.

``FederatedAverager`` drives the classic federated round (McMahan et al.
2017) on top of the framework's existing pieces instead of inventing new
ones: local client steps reuse the eager autograd loop + a throwaway
``optimizer.SGD``; the server update reuses ANY ``paddle_tpu.optimizer``
(SGD(lr=1) = plain FedAvg, AdamW = FedAdam-style server adaptivity) by
handing it the aggregated update as a pseudo-gradient; and the
cross-client aggregation is one ``federated_weighted_mean`` over the
flattened trainable deltas — through the metered collective chokepoint,
so ``collective_bytes_total{op=federated_sum}`` reports exactly the
aggregated payload bytes.

LoRA multi-task fine-tuning composes for free: run
``incubate.lora.apply_lora`` (or ``mark_only_lora_trainable``) on the
model first and only the adapters are trainable, so only adapter deltas
travel — the aggregation payload shrinks from the full model to
O(r * (in+out)) per wrapped layer (docs/FEDERATED.md has the recipe).

Observability discipline (PR 2-7): ``federated_round_total``,
``federated_client_examples``, ``federated_client_dropped_total`` and
``federated_round_ms`` in the monitor registry; ``federated_round`` /
``client_update`` / ``federated_aggregate`` spans; a ``federated_round``
flight-recorder digest; and the ``federated/round`` failpoint at each
client's update — an injected fault drops THAT client and the round
completes with the surviving cohort. All of it is inert-by-default: no
metric family, span, or import exists until a FederatedAverager runs
(tests/test_federated_gate.py pins this).
"""
import time

import numpy as np

from .. import monitor as _monitor
from .. import trace as _trace
from ..core.tape import no_grad
from ..core.tensor import Tensor, to_tensor
from ..monitor import blackbox_lazy as _blackbox  # import-free recorder facade (ISSUE 12)
from ..testing import failpoints as _fp
from .primitives import federated_weighted_mean

__all__ = ["FederatedAverager", "HANDOFF_SCHEMA"]

#: The client->server adapter-payload transfer edge (ISSUE 13; docs/
#: ANALYSIS.md "Declaring a transfer edge"). Statically extracted and
#: baseline-pinned by analysis/handoff_schema.py: the LoRA multi-task
#: byte math (rounds * C * (adapter_params * 4 + 4), asserted exactly in
#: tests/test_federated.py) depends on this payload staying a flat f32
#: delta vector + one example count — drift fails lint.
HANDOFF_SCHEMA = {
    "edge": "federated_adapter",
    "producer": ("paddle_tpu/federated/averaging.py::"
                 "FederatedAverager._client_update"),
    "consumer": ("paddle_tpu/federated/averaging.py::"
                 "FederatedAverager.run_round"),
    "runtime_checked": False,
    "doc": "one client's round contribution: the flattened trainable "
           "deltas (adapter-only under LoRA) weighted by its example "
           "count through ONE federated_weighted_mean",
    "payload": {
        "delta": {"shape": ("n_trainable",), "dtype": "float32",
                  "layout": "flat concat of trainable params in "
                            "snapshot order"},
        "n_examples": {"kind": "scalar", "dtype": "int"},
    },
}

_M = None   # lazy federated metric family handles


def _metrics():
    global _M
    if _M is None:
        _M = {
            "rounds": _monitor.counter(
                "federated_round_total",
                "completed federated rounds by algorithm",
                labelnames=("algorithm",)),
            "examples": _monitor.histogram(
                "federated_client_examples",
                "examples processed per client update (count = client "
                "updates, sum = total examples)",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                         4096, 16384, 65536)),
            "dropped": _monitor.counter(
                "federated_client_dropped_total",
                "client updates dropped mid-round (failpoint or organic "
                "error); the round completes with the surviving cohort",
                labelnames=("reason",)),
            "round_ms": _monitor.histogram(
                "federated_round_ms",
                "wall time of one federated round (sampling + local "
                "updates + aggregation + server update)"),
        }
    return _M


class FederatedAverager:
    """FedAvg/FedSGD driver: sample a cohort, run per-client local steps,
    aggregate example-weighted deltas through ``federated_weighted_mean``,
    apply the update with the server optimizer.

    ``client_data`` is a sequence of client datasets — each a list of
    ``(inputs, labels)`` numpy batch tuples (``federated.partition_clients``
    builds these). ``loss_fn(outputs, labels)`` is any callable (a loss
    Layer or function). Only params with ``trainable=True`` participate —
    freeze the rest (e.g. ``incubate.lora.mark_only_lora_trainable``) and
    their values never leave the server.

    ``algorithm="fedavg"``: each client runs ``local_steps`` of
    SGD(``local_lr``), the delta ``local - global`` aggregates, and the
    server optimizer consumes ``-delta`` as a pseudo-gradient (SGD(lr=1)
    reproduces textbook FedAvg; an adaptive server optimizer gives
    FedAdam/FedOpt behavior). ``algorithm="fedsgd"``: clients compute one
    gradient, no local step; the aggregated gradient feeds the server
    optimizer directly."""

    def __init__(self, model, loss_fn, client_data, server_optimizer=None,
                 clients_per_round=None, local_steps=1, local_lr=0.1,
                 algorithm="fedavg", seed=0):
        if algorithm not in ("fedavg", "fedsgd"):
            raise ValueError(f"algorithm must be 'fedavg' or 'fedsgd', "
                             f"got {algorithm!r}")
        if not client_data:
            raise ValueError("client_data is empty — nothing to federate")
        self.model = model
        self.loss_fn = loss_fn
        self.client_data = list(client_data)
        self.n_clients = len(self.client_data)
        self.algorithm = algorithm
        self.local_steps = int(local_steps)
        self.local_lr = float(local_lr)
        self.clients_per_round = int(clients_per_round or self.n_clients)
        if not 1 <= self.clients_per_round <= self.n_clients:
            raise ValueError(
                f"clients_per_round must be in [1, {self.n_clients}], got "
                f"{self.clients_per_round}")
        self._trainable = [(n, p) for n, p in model.named_parameters()
                           if getattr(p, "trainable", True)]
        if not self._trainable:
            raise ValueError("model has no trainable parameters (did "
                             "mark_only_lora_trainable run before LoRA "
                             "was applied?)")
        from ..optimizer import SGD

        if server_optimizer is None:
            server_optimizer = SGD(
                learning_rate=1.0,
                parameters=[p for _, p in self._trainable])
        self.server_optimizer = server_optimizer
        self._rng = np.random.RandomState(seed)
        self.round_num = 0
        self._numerics = None   # lazy telescope hook (FLAGS_numerics)
        # one shared local optimizer: plain SGD is stateless, so reusing
        # it across clients leaks nothing and keeps ONE jitted update rule
        # instead of a fresh jit wrapper (and compile) per client
        self._local_opt = SGD(learning_rate=self.local_lr,
                              parameters=[p for _, p in self._trainable])
        # flatten/unflatten layout over the trainable set (fixed per run)
        self._shapes = [tuple(p.shape) for _, p in self._trainable]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        self._offsets = np.cumsum([0] + self._sizes)

    # -- parameter plumbing ------------------------------------------------
    def _snapshot(self):
        return [np.array(np.asarray(p._data), copy=True)
                for _, p in self._trainable]

    def _restore(self, vals):
        for (_, p), v in zip(self._trainable, vals):
            p.set_value(v)

    def _flatten(self, vals):
        return np.concatenate([np.asarray(v, np.float32).ravel()
                               for v in vals])

    def _unflatten(self, flat):
        return [np.asarray(flat[a:b], np.float32).reshape(s)
                for a, b, s in zip(self._offsets[:-1], self._offsets[1:],
                                   self._shapes)]

    # -- one client's contribution -----------------------------------------
    def _client_update(self, cid, global_vals):
        """Run one client's local work from the current global params;
        returns (flat delta-or-grad float32 vector, n_examples). The
        caller restores global params afterwards."""
        batches = self.client_data[cid]
        if not batches:
            raise ValueError(f"client {cid} has no batches")
        n_examples = 0
        if self.algorithm == "fedsgd":
            x, y = batches[0]
            loss = self.loss_fn(self.model(to_tensor(x)), to_tensor(y))
            for _, p in self._trainable:
                p.clear_grad()
            loss.backward()
            grads = [np.asarray(p.grad._data) if p.grad is not None
                     else np.zeros(p.shape, np.float32)
                     for _, p in self._trainable]
            for _, p in self._trainable:
                p.clear_grad()
            return self._flatten(grads), len(x)
        local_opt = self._local_opt
        for step in range(self.local_steps):
            x, y = batches[step % len(batches)]
            loss = self.loss_fn(self.model(to_tensor(x)), to_tensor(y))
            loss.backward()
            local_opt.step()
            local_opt.clear_grad()
            n_examples += len(x)
        delta = [np.asarray(p._data) - g
                 for (_, p), g in zip(self._trainable, global_vals)]
        return self._flatten(delta), n_examples

    def _apply_server_update(self, flat_update):
        """Feed the aggregated update to the server optimizer as a
        pseudo-gradient: FedAvg descends along -delta (so the optimizer's
        `p -= lr * g` applies +delta at lr=1), FedSGD along the averaged
        gradient itself."""
        sign = -1.0 if self.algorithm == "fedavg" else 1.0
        for (_, p), part in zip(self._trainable,
                                self._unflatten(sign * flat_update)):
            p.grad = Tensor(part.astype(np.asarray(p._data).dtype),
                            stop_gradient=True)
        self.server_optimizer.step()
        self.server_optimizer.clear_grad()

    # -- the round ---------------------------------------------------------
    def run_round(self):
        """One federated round. Returns a stats dict: cohort/survivor/
        dropped counts, total examples, and the aggregated update's L2
        norm. A client whose update raises (the ``federated/round``
        failpoint, or an organic per-client error) is dropped; the round
        completes with the survivors. Raises only when EVERY sampled
        client fails — there is nothing to aggregate."""
        rnd = self.round_num
        t0 = time.perf_counter()
        cohort = sorted(self._rng.choice(
            self.n_clients, size=self.clients_per_round, replace=False))
        global_vals = self._snapshot()
        deltas, weights, dropped = [], [], 0
        with _trace.span("federated_round", subsystem="federated",
                         round=rnd, cohort=len(cohort)):
            for cid in cohort:
                try:
                    with _trace.span("client_update", subsystem="federated",
                                     client=int(cid)) as sp:
                        _fp.failpoint("federated/round")
                        vec, n_ex = self._client_update(cid, global_vals)
                        sp.set(examples=n_ex)
                except Exception as e:
                    # per-client isolation, like serving's per-slot
                    # errors: the client is dropped (injected fault or
                    # organic error alike), its partial update shed, and
                    # the round completes with the survivors
                    dropped += 1
                    if _monitor.is_enabled():
                        reason = ("failpoint"
                                  if isinstance(e, _fp.FailpointError)
                                  else "error")
                        _metrics()["dropped"].labels(reason=reason).inc()
                    self._restore(global_vals)
                    for _, p in self._trainable:
                        p.clear_grad()   # a death mid-backward must not
                        #                  bleed grads into the next client
                    continue
                self._restore(global_vals)
                deltas.append(vec)
                weights.append(float(n_ex))
                if _monitor.is_enabled():
                    _metrics()["examples"].observe(n_ex)
            if not deltas:
                raise RuntimeError(
                    f"federated round {rnd}: every client in the "
                    f"{len(cohort)}-client cohort failed; nothing to "
                    "aggregate")
            with _trace.span("federated_aggregate", subsystem="federated",
                             clients=len(deltas)):
                stacked = np.stack(deltas)          # [survivors, n_params]
                agg = np.asarray(federated_weighted_mean(
                    stacked, np.asarray(weights, np.float32)))
            self._note_numerics(rnd, agg, global_vals)
            self._apply_server_update(agg)
        self.round_num += 1
        if _monitor.is_enabled():
            m = _metrics()
            m["rounds"].labels(algorithm=self.algorithm).inc()
            m["round_ms"].observe((time.perf_counter() - t0) * 1e3)
        stats = {"round": rnd, "cohort": len(cohort),
                 "survivors": len(deltas), "dropped": dropped,
                 "examples": int(sum(weights)),
                 "update_norm": float(np.linalg.norm(agg))}
        _blackbox.note("federated_round", **stats)
        return stats

    def _note_numerics(self, rnd, agg, global_vals):
        """FLAGS_numerics: feed the round's aggregate through the same
        telescope path the trainer uses — the cohort-weighted delta norm
        (``agg`` is already the example-weighted mean, so its norm IS the
        cohort-weighted one) and the update/param ratio land as
        ``numerics_*{layer="federated/round"}`` series with the full
        ring/EMA drift detection behind them. One flag check when unset:
        the plain path never imports the telescope (gate-pinned to zero
        drift by tests/test_numerics_gate.py)."""
        from .. import flags as _flags

        if not _flags.get_flag("numerics"):
            return
        from ..monitor import numerics as _numerics

        if self._numerics is None:
            self._numerics = _numerics.NumericsMonitor(
                ["federated/round"], source="federated")
        agg = np.asarray(agg, np.float32)
        delta_norm = float(np.linalg.norm(agg))
        param_norm = float(np.linalg.norm(self._flatten(global_vals)))
        finite = np.isfinite(agg)
        self._numerics.observe({
            "grad_norm": np.asarray([delta_norm], np.float32),
            "grad_absmax": np.asarray(
                [np.max(np.abs(agg)) if agg.size else 0.0], np.float32),
            "nonfinite": np.asarray([float(np.sum(~finite))], np.float32),
            "param_norm": np.asarray([param_norm], np.float32),
            "update_norm": np.asarray([delta_norm], np.float32),
            "update_ratio": np.asarray(
                [delta_norm / (param_norm + 1e-12)], np.float32),
        }, step=rnd)

    def run(self, rounds):
        """Drive ``rounds`` rounds; returns the per-round stats list."""
        return [self.run_round() for _ in range(int(rounds))]

    # -- evaluation --------------------------------------------------------
    def evaluate(self):
        """Example-weighted mean loss of the CURRENT global model over
        every client's data (the FedAvg objective being minimized)."""
        total, n = 0.0, 0
        with no_grad():
            for batches in self.client_data:
                for x, y in batches:
                    loss = self.loss_fn(self.model(to_tensor(x)),
                                        to_tensor(y))
                    total += float(np.asarray(loss._data)) * len(x)
                    n += len(x)
        return total / max(n, 1)
