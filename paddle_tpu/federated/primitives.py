"""DrJAX-style federated MapReduce primitives over a named ``clients`` axis.

Per DrJAX (PAPERS.md, arXiv:2403.07128): a federated computation is a
sharded map over a *clients* axis plus differentiable reduces. Here the
map is ``jax.vmap(fn, axis_name="clients")`` — so a reduce inside the
mapped body is a real named-axis collective (``jax.lax.psum`` on
``"clients"``) that XLA differentiates like any other primitive, and when
the leading clients dimension of the inputs is sharded over a mesh
``clients`` axis (``distributed.mesh.client_mesh``), GSPMD partitions the
per-client work across devices and schedules the reduce on the ICI. The
same program runs unchanged on 1 device (clients stacked in one shard) or
N (clients spread) — placement is sharding, not code.

Every cross-client reduce flows through
``distributed.collective.client_reduce`` — the framework's collective
chokepoint — so federated aggregation is byte-metered
(``collective_bytes_total{op=federated_sum}``), span-traced
(``collective/federated_sum``), and failpoint-covered
(``collective/call``) exactly like dp all-reduces, and will inherit the
planned quantized-reduce path (ROADMAP item 2) for free.

Two placements for values (DrJAX's federated types, structurally):

- *server* — an ordinary array/Tensor;
- *clients* — an array whose LEADING axis is the clients dimension
  (``broadcast_to_clients`` lifts server -> clients; ``federated_sum`` /
  ``federated_mean`` / ``federated_weighted_mean`` lower clients ->
  server).

Inside a ``client_map`` body the clients axis is a *named* vmap axis, so
the reduce primitives switch to psum/pmean on it automatically (the body
sees per-client values, the reduce returns the replicated aggregate).
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..distributed import collective as _coll

__all__ = [
    "CLIENTS_AXIS", "in_client_map", "num_clients", "broadcast_to_clients",
    "client_map", "federated_sum", "federated_mean",
    "federated_weighted_mean",
]

CLIENTS_AXIS = "clients"

_MAP_DEPTH = []   # truthy while a client_map body is being traced/executed


def in_client_map():
    """True inside a ``client_map`` body (the ``clients`` vmap axis is in
    scope, so reduces lower to named-axis collectives)."""
    return bool(_MAP_DEPTH)


@contextlib.contextmanager
def _map_scope():
    _MAP_DEPTH.append(CLIENTS_AXIS)
    try:
        yield
    finally:
        _MAP_DEPTH.pop()


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _shard_clients(arr, mesh):
    """Pin a clients-leading array's leading axis onto the mesh 'clients'
    axis. Under a trace this is a sharding constraint; eagerly it is a
    device_put — either way XLA sees the same placement."""
    if mesh is None or CLIENTS_AXIS not in mesh.axis_names:
        return arr
    sh = NamedSharding(mesh, P(CLIENTS_AXIS))
    if isinstance(arr, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(arr, sh)
    return jax.device_put(jnp.asarray(arr), sh)


def num_clients(x=None):
    """The clients-axis size: inside a ``client_map`` body this is the
    named-axis size (``psum(1, 'clients')``); outside, the leading-axis
    length of the given clients-placed array."""
    if in_client_map():
        return jax.lax.psum(1, CLIENTS_AXIS)
    if x is None:
        raise ValueError("num_clients() outside client_map needs a "
                         "clients-placed array to read the axis from")
    return int(_unwrap(x).shape[0])


def broadcast_to_clients(x, n_clients, mesh=None):
    """Server -> clients placement: replicate ``x`` along a new leading
    clients axis (shape ``[n_clients, *x.shape]``). With a ``clients``
    mesh, the result is sharded over that axis — on TPU the broadcast is
    then a real transfer; on one device it is a view-cheap tile. Returns
    the same kind (Tensor in -> Tensor out); the broadcast is
    differentiable (its reverse is a cross-client sum), and Tensor inputs
    keep their tape link through ``dispatch.apply``."""
    n = int(n_clients)
    if isinstance(x, Tensor):
        from ..core.dispatch import apply

        out = apply(lambda v: jnp.broadcast_to(v[None], (n,) + v.shape), x)
        out._data = _shard_clients(out._data, mesh)
        return out
    arr = jnp.asarray(x)
    return _shard_clients(jnp.broadcast_to(arr[None], (n,) + arr.shape),
                          mesh)


def client_map(fn, *args, mesh=None, in_axes=0, out_axes=0):
    """Map ``fn`` over the clients axis — DrJAX's ``map_fn``.

    ``fn`` receives one client's slice of each mapped arg (leading axis
    stripped) and runs with the ``clients`` axis IN SCOPE: ``federated_*``
    reduces inside the body lower to named-axis collectives and return the
    replicated aggregate to every client. ``in_axes`` follows ``jax.vmap``
    (``None`` broadcasts a server-placed value to every client without
    materializing copies). With ``mesh`` (a Mesh carrying a ``clients``
    axis, e.g. ``distributed.mesh.client_mesh``), mapped inputs are
    sharded over it so the per-client work partitions across devices.

    Tensor args ride the autograd tape (the whole mapped computation is
    one vjp node); raw arrays compose with jax.grad/jit as usual. The
    result keeps the clients leading axis — pass it through a
    ``federated_*`` reduce before it escapes a federated API
    (analysis/source_lint.py's ``nonreduced-client-output`` rule holds
    paddle_tpu's own federated code to that)."""
    def body(*xs):
        with _map_scope():
            return fn(*xs)

    mapped = jax.vmap(body, in_axes=in_axes, out_axes=out_axes,
                      axis_name=CLIENTS_AXIS)
    if mesh is not None:
        axes = (in_axes if isinstance(in_axes, (tuple, list))
                else [in_axes] * len(args))
        bad = [ax for ax in axes if ax not in (None, 0)]
        if bad:
            raise ValueError(
                "client_map(mesh=...) shards the LEADING axis over the "
                "'clients' mesh axis; mapped in_axes must be 0 (or None "
                f"for broadcast), got {list(axes)} — move the clients "
                "dimension to axis 0 (e.g. jnp.moveaxis) before sharding")
        for a, ax in zip(args, axes):
            if ax is None:
                continue
            # placement-only move (values identical): a Tensor keeps its
            # identity — and with it its tape link — by resharding its
            # buffer in place, exactly like the in-place collectives do
            if isinstance(a, Tensor):
                a._data = _shard_clients(a._data, mesh)
        args = tuple(a if (ax is None or isinstance(a, Tensor))
                     else _shard_clients(a, mesh)
                     for a, ax in zip(args, axes))
    if any(isinstance(a, Tensor) for a in args):
        from ..core.dispatch import apply

        return apply(mapped, *args)
    return mapped(*args)


def federated_sum(x):
    """Differentiable cross-client sum — the MapReduce reduce. Inside a
    ``client_map`` body: ``psum`` over the named ``clients`` axis (every
    client receives the replicated total); outside: reduce the leading
    clients axis to a server-placed value. Either way the reduce goes
    through ``distributed.collective.client_reduce`` and is metered as
    ``collective_bytes_total{op=federated_sum}``."""
    return _coll.client_reduce(x, op=_coll.ReduceOp.SUM,
                               axis_name=CLIENTS_AXIS,
                               placed=in_client_map())


def federated_mean(x):
    """Uniform cross-client mean: ``federated_sum(x) / n_clients`` (one
    metered reduce plus a free scalar divide)."""
    n = num_clients(None if in_client_map() else x)
    return federated_sum(x) / n


def federated_weighted_mean(x, w):
    """Example-weighted cross-client mean — FedAvg's aggregation:
    ``sum_c(w_c * x_c) / sum_c(w_c)``. ``w`` is one non-negative scalar
    per client (inside ``client_map``: this client's weight; outside: a
    ``[n_clients]`` vector broadcast against ``x``'s trailing dims). Both
    sums are metered ``federated_sum`` reduces, so the numerator's byte
    count is exactly the aggregated payload (the adapter bytes in a LoRA
    FedAvg round)."""
    if not in_client_map():
        warr = jnp.asarray(_unwrap(w), dtype=jnp.float32)
        xa = _unwrap(x)
        w = warr.reshape((-1,) + (1,) * (np.ndim(xa) - 1))
    num = federated_sum(x * w)
    den = federated_sum(w)
    return num / den
