"""Client-partitioning helpers: turn one dataset into per-client shards.

A *client dataset* throughout ``paddle_tpu.federated`` is a plain list of
``(inputs, labels)`` numpy batch tuples — the shape ``FederatedAverager``
consumes and ``partition_clients`` produces. Partitioning is deterministic
(contiguous, near-equal shards, no RNG) so federated runs are exactly
reproducible and a client's data never silently migrates between runs.
"""
import numpy as np

__all__ = ["partition_clients"]


def _as_example_arrays(data, seq_len):
    """Normalize the supported inputs into (X, Y) example arrays."""
    if hasattr(data, "examples"):          # dataset.TinyCorpus and friends
        return data.examples(seq_len=seq_len)
    if isinstance(data, (tuple, list)) and len(data) == 2:
        return np.asarray(data[0]), np.asarray(data[1])
    raise TypeError(
        "partition_clients takes a corpus with .examples(seq_len=) (e.g. "
        "paddle_tpu.dataset.tiny_corpus()) or an (inputs, labels) array "
        f"pair, got {type(data)}")


def partition_clients(data, n_clients, batch_size=8, seq_len=16):
    """Shard a dataset into ``n_clients`` deterministic client datasets.

    ``data`` is either a corpus exposing ``examples(seq_len=)`` (e.g.
    ``paddle_tpu.dataset.tiny_corpus()``) or an ``(inputs, labels)`` pair
    of aligned arrays. Examples are split into contiguous, near-equal
    shards (``np.array_split`` semantics: the first ``len % n`` clients
    get one extra example — naturally *unequal* client example counts,
    which is what ``federated_weighted_mean`` weighting is for), then each
    shard is chunked into ``(inputs, labels)`` batches of ``batch_size``.

    Returns a list of ``n_clients`` lists of batch tuples; every client
    has at least one batch as long as there are >= n_clients examples."""
    if n_clients < 1:
        raise ValueError(f"n_clients must be >= 1, got {n_clients}")
    X, Y = _as_example_arrays(data, seq_len)
    if len(X) != len(Y):
        raise ValueError(f"inputs/labels length mismatch: {len(X)} vs "
                         f"{len(Y)}")
    if len(X) < n_clients:
        raise ValueError(f"cannot shard {len(X)} examples over "
                         f"{n_clients} clients")
    clients = []
    for xs, ys in zip(np.array_split(X, n_clients),
                      np.array_split(Y, n_clients)):
        batches = [(xs[i:i + batch_size], ys[i:i + batch_size])
                   for i in range(0, len(xs), batch_size)]
        clients.append(batches)
    return clients
