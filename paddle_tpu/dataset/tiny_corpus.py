"""An in-repo, deterministic character-level corpus for the LM book tests.

No network, no files, no RNG: the text is expanded from fixed sentence
templates at import cost only (a few KB), so every run — the LM book test
(tests/test_book_lm.py), the federated client partitioner, CI on any
machine — sees byte-identical data. The templates are deliberately
low-entropy (a small closed vocabulary, rigid syntax) so a tiny GPT
reaches a meaningful next-char loss in a few hundred CPU steps while
still having enough structure that convergence proves real learning, not
memorizing one string.
"""
import numpy as np

__all__ = ["TinyCorpus", "tiny_corpus"]

_SUBJECTS = ("the cat", "the dog", "the bird", "a fox", "the owl",
             "the fish", "a crab", "the mouse")
_VERBS = ("sees", "finds", "follows", "watches", "likes", "meets")
_OBJECTS = ("the moon", "the river", "a tree", "the hill", "a star",
            "the sea", "the sun", "a leaf")


def _book_text(repeats=3):
    """Expand the templates into a deterministic little 'book'."""
    lines = []
    for r in range(repeats):
        for i, s in enumerate(_SUBJECTS):
            v = _VERBS[(i + r) % len(_VERBS)]
            o = _OBJECTS[(i * 3 + r) % len(_OBJECTS)]
            lines.append(f"{s} {v} {o}.")
    return " ".join(lines) + "\n"


class TinyCorpus:
    """A char-level corpus: text, vocab, encode/decode, and next-token
    example windows — everything the book test and the federated
    partitioner need, with zero I/O."""

    def __init__(self, text):
        self.text = text
        chars = sorted(set(text))
        self.stoi = {c: i for i, c in enumerate(chars)}
        self.itos = {i: c for i, c in enumerate(chars)}
        self.ids = np.asarray([self.stoi[c] for c in text], np.int32)

    @property
    def vocab_size(self):
        return len(self.stoi)

    def encode(self, s):
        """Text -> int32 ids; raises KeyError on out-of-vocabulary chars
        (the corpus IS the vocabulary)."""
        return np.asarray([self.stoi[c] for c in s], np.int32)

    def decode(self, ids):
        return "".join(self.itos[int(i)] for i in np.asarray(ids).ravel())

    def examples(self, seq_len=16, stride=None):
        """Sliding next-token windows: X[i] = ids[i:i+L], Y[i] = the same
        window shifted one char (the labels GPTPretrainLoss expects).
        ``stride`` defaults to seq_len (non-overlapping windows)."""
        stride = int(stride or seq_len)
        L = int(seq_len)
        # last valid start is len-L-1 (Y needs one lookahead char)
        starts = range(0, len(self.ids) - L, stride)
        X = np.stack([self.ids[s:s + L] for s in starts])
        Y = np.stack([self.ids[s + 1:s + L + 1] for s in starts])
        return X, Y

    def __len__(self):
        return len(self.ids)

    def __repr__(self):
        return (f"TinyCorpus(chars={len(self.ids)}, "
                f"vocab={self.vocab_size})")


def tiny_corpus(repeats=3):
    """The deterministic in-repo corpus (same text for the same
    ``repeats``, always)."""
    return TinyCorpus(_book_text(repeats))
