"""paddle.dataset parity (python/paddle/dataset/) — the fluid-era
reader-creator API: `paddle.dataset.uci_housing.train()` returns a generator
creator yielding per-sample tuples, composable with paddle.batch /
paddle.reader decorators.

TPU-native stance: these are thin adapters over the 2.x map-style datasets in
paddle_tpu.vision.datasets / paddle_tpu.text.datasets (which parse the real
archive formats when given data files and fall back to deterministic synthetic
samples without them); the reader-creator protocol itself is pure python.
"""
import types

import numpy as np

from .tiny_corpus import TinyCorpus, tiny_corpus

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov", "movielens",
           "wmt14", "wmt16", "conll05", "flowers", "voc2012", "common",
           "TinyCorpus", "tiny_corpus"]


def _creator(ds_factory, mapper=None):
    def reader():
        ds = ds_factory()
        for i in range(len(ds)):
            sample = ds[i]
            yield mapper(sample) if mapper else tuple(
                np.asarray(getattr(p, "_data", p)) for p in sample)

    return reader


def _module(name, **fns):
    m = types.ModuleType(f"{__name__}.{name}")
    for k, v in fns.items():
        setattr(m, k, v)
    return m


def _mnist_mod():
    from ..vision.datasets import MNIST

    return _module(
        "mnist",
        train=lambda: _creator(lambda: MNIST(mode="train")),
        test=lambda: _creator(lambda: MNIST(mode="test")),
    )


def _cifar_mod():
    from ..vision.datasets import Cifar10, Cifar100

    return _module(
        "cifar",
        train10=lambda: _creator(lambda: Cifar10(mode="train")),
        test10=lambda: _creator(lambda: Cifar10(mode="test")),
        train100=lambda: _creator(lambda: Cifar100(mode="train")),
        test100=lambda: _creator(lambda: Cifar100(mode="test")),
    )


def _uci_mod():
    from ..text.datasets import UCIHousing

    return _module(
        "uci_housing",
        train=lambda: _creator(lambda: UCIHousing(mode="train")),
        test=lambda: _creator(lambda: UCIHousing(mode="test")),
        feature_names=["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                       "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"],
    )


def _imdb_mod():
    from ..text.datasets import Imdb

    def word_dict(cutoff=150):
        ds = Imdb(mode="train", cutoff=cutoff)
        if hasattr(ds, "word_idx"):      # real aclImdb archive parsed
            return ds.word_idx
        # synthetic fallback: deterministic ids over the synthetic vocab
        return {f"w{i}".encode(): i for i in range(ds.VOCAB)}

    return _module(
        "imdb",
        train=lambda word_idx=None: _creator(lambda: Imdb(mode="train")),
        test=lambda word_idx=None: _creator(lambda: Imdb(mode="test")),
        word_dict=word_dict,
    )


def _imikolov_mod():
    from ..text.datasets import Imikolov

    def build_dict(min_word_freq=50):
        return Imikolov(mode="train", min_word_freq=min_word_freq).word_idx

    return _module(
        "imikolov",
        train=lambda word_idx=None, n=5: _creator(
            lambda: Imikolov(mode="train", window_size=n)),
        test=lambda word_idx=None, n=5: _creator(
            lambda: Imikolov(mode="test", window_size=n)),
        build_dict=build_dict,
    )


def _movielens_mod():
    from ..text.datasets import Movielens

    return _module(
        "movielens",
        train=lambda: _creator(lambda: Movielens(mode="train")),
        test=lambda: _creator(lambda: Movielens(mode="test")),
    )


def _wmt_mod(cls_name):
    def make():
        from .. import text

        cls = getattr(text, cls_name)
        return _module(
            cls_name.lower(),
            train=lambda dict_size=30000: _creator(
                lambda: cls(mode="train", dict_size=dict_size)
                if cls_name == "WMT14" else cls(mode="train")),
            test=lambda dict_size=30000: _creator(
                lambda: cls(mode="test", dict_size=dict_size)
                if cls_name == "WMT14" else cls(mode="test")),
        )

    return make


def _conll05_mod():
    from ..text.datasets import Conll05st

    return _module(
        "conll05",
        test=lambda: _creator(lambda: Conll05st(mode="test")),
        get_dict=lambda: Conll05st(mode="test").get_dict(),
    )


def _flowers_mod():
    from ..vision.datasets import Flowers

    return _module(
        "flowers",
        train=lambda: _creator(lambda: Flowers(mode="train")),
        test=lambda: _creator(lambda: Flowers(mode="test")),
        valid=lambda: _creator(lambda: Flowers(mode="valid")),
    )


def _voc_mod():
    from ..vision.datasets import VOC2012

    return _module(
        "voc2012",
        train=lambda: _creator(lambda: VOC2012(mode="train")),
        test=lambda: _creator(lambda: VOC2012(mode="test")),
        val=lambda: _creator(lambda: VOC2012(mode="valid")),
    )


_LAZY = {
    "mnist": _mnist_mod,
    "cifar": _cifar_mod,
    "uci_housing": _uci_mod,
    "imdb": _imdb_mod,
    "imikolov": _imikolov_mod,
    "movielens": _movielens_mod,
    "wmt14": _wmt_mod("WMT14"),
    "wmt16": _wmt_mod("WMT16"),
    "conll05": _conll05_mod,
    "flowers": _flowers_mod,
    "voc2012": _voc_mod,
}


def __getattr__(name):
    if name in _LAZY:
        mod = _LAZY[name]()
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


common = _module("common", md5file=lambda path: __import__("hashlib").md5(
    open(path, "rb").read()).hexdigest())
