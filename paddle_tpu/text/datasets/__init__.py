"""Text datasets (python/paddle/text/datasets parity: Conll05st, Imdb, Imikolov,
Movielens, UCIHousing, WMT14, WMT16).

Real corpora parse when `data_file=` points at the standard archive (the SAME
formats the reference downloads: aclImdb tar for Imdb, PTB simple-examples tar
for Imikolov, ml-1m zip for Movielens, whitespace table for UCIHousing).
Zero-egress environment: with no data_file, synthetic token streams with the
original sample shapes keep pipelines runnable — clearly a fallback, not data.
"""
import collections
import os
import re
import string
import tarfile
import zipfile

import numpy as np

from ...io.dataset import Dataset


class _SyntheticTextDataset(Dataset):
    VOCAB = 10000
    SEQ_LEN = 32
    N = 2000

    def __init__(self, mode="train", seed=0, **kwargs):
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.data = rng.randint(1, self.VOCAB, size=(self.N, self.SEQ_LEN)).astype(np.int64)
        self.labels = rng.randint(0, 2, size=self.N).astype(np.int64)

    def __getitem__(self, idx):
        return self.data[idx], np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return self.N


class Imdb(_SyntheticTextDataset):
    """Sentiment classification: (token_ids, label).

    Real path (reference imdb.py:92-137 parity): parse the aclImdb tar —
    word dict built over train+test with `cutoff` frequency pruning, docs
    tokenized by punctuation-strip + lower + split, pos label 0 / neg 1."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, cutoff)
        else:
            super().__init__(mode=mode, seed=100)

    def _load_real(self, data_file, cutoff):
        """ONE decompression pass: docs collected keyed by (split, part) feed
        both the dict build and the labeled load. Tolerates a leading './'
        in member names (tar czf ./aclImdb produces them)."""
        pat = re.compile(r"(?:\./)?aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        table = bytes.maketrans(b"", b"")
        punct = string.punctuation.encode()
        grouped = collections.defaultdict(list)
        with tarfile.open(data_file) as tarf:
            tf = tarf.next()
            while tf is not None:
                m = pat.match(tf.name)
                if m:
                    raw = tarf.extractfile(tf).read().rstrip(b"\n\r")
                    grouped[m.groups()].append(
                        raw.translate(table, punct).lower().split())
                tf = tarf.next()
        if not grouped:
            raise ValueError(
                f"{data_file}: no aclImdb/<split>/<pos|neg>/*.txt members "
                "found — is this the aclImdb archive?")
        word_freq = collections.defaultdict(int)
        for docs in grouped.values():
            for doc in docs:
                for w in doc:
                    word_freq[w] += 1
        kept = sorted(((w, f) for w, f in word_freq.items() if f > cutoff),
                      key=lambda x: (-x[1], x[0]))
        self.word_idx = {w: i for i, (w, _) in enumerate(kept)}
        self.word_idx[b"<unk>"] = len(self.word_idx)
        unk = self.word_idx[b"<unk>"]
        self.docs, labels = [], []
        for label, part in ((0, "pos"), (1, "neg")):
            for doc in grouped.get((self.mode, part), []):
                self.docs.append(np.array(
                    [self.word_idx.get(w, unk) for w in doc], np.int64))
                labels.append(label)
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        if hasattr(self, "docs"):
            return self.docs[idx], np.array([self.labels[idx]], np.int64)
        return super().__getitem__(idx)

    def __len__(self):
        if hasattr(self, "docs"):
            return len(self.docs)
        return super().__len__()


class Imikolov(_SyntheticTextDataset):
    """Language-model n-grams / sequences over PTB.

    Real path (reference imikolov.py parity): parse the simple-examples tar
    (ptb.train.txt / ptb.valid.txt members), word dict with <s>/<e>/<unk> and
    min_word_freq pruning; NGRAM windows or SEQ (src, trg) pairs."""

    VOCAB = 2000
    SEQ_LEN = 5

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train",
                 min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = mode.lower()
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, min_word_freq)
        else:
            self.SEQ_LEN = max(2, window_size)
            super().__init__(mode=mode, seed=200)

    def _load_real(self, data_file, min_word_freq):
        word_freq = collections.defaultdict(int)
        with tarfile.open(data_file) as tarf:
            names = tarf.getnames()
            # tolerate archives without the leading "./"
            trainn = next(n for n in names if n.endswith("ptb.train.txt"))
            validn = next(n for n in names if n.endswith("ptb.valid.txt"))
            for n in (trainn, validn):
                for line in tarf.extractfile(n).read().decode().splitlines():
                    for w in line.strip().split():
                        word_freq[w] += 1
                    word_freq["<s>"] += 1
                    word_freq["<e>"] += 1
            word_freq.pop("<unk>", None)
            kept = sorted(((w, f) for w, f in word_freq.items()
                           if f >= min_word_freq),
                          key=lambda x: (-x[1], x[0]))
            self.word_idx = {w: i for i, (w, _) in enumerate(kept)}
            self.word_idx["<unk>"] = len(self.word_idx)
            unk = self.word_idx["<unk>"]
            target = trainn if self.mode == "train" else validn
            samples = []
            for line in tarf.extractfile(target).read().decode().splitlines():
                ids = ([self.word_idx["<s>"]]
                       + [self.word_idx.get(w, unk)
                          for w in line.strip().split()]
                       + [self.word_idx["<e>"]])
                if self.data_type == "NGRAM":
                    if self.window_size <= 0 or len(ids) < self.window_size:
                        continue
                    for i in range(self.window_size, len(ids) + 1):
                        samples.append(ids[i - self.window_size:i])
                else:
                    samples.append(ids)
            self.samples = samples

    def __getitem__(self, idx):
        if hasattr(self, "samples"):
            row = np.array(self.samples[idx], np.int64)
        else:
            row = self.data[idx]
        if self.data_type == "SEQ":
            return row[:-1], row[1:]  # equal-length shifted pair, both paths
        return row[:-1], row[-1:]

    def __len__(self):
        if hasattr(self, "samples"):
            return len(self.samples)
        return super().__len__()


class Movielens(Dataset):
    """Rating prediction (user, movie, rating).

    Real path (reference movielens.py parity, core triple): parse the ml-1m
    zip's ratings.dat (UserID::MovieID::Rating::Timestamp), split train/test
    by test_ratio with rand_seed."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1, rand_seed=0, download=True):
        rng = np.random.RandomState(rand_seed + (0 if mode == "train" else 1))
        if data_file and os.path.exists(data_file):
            with zipfile.ZipFile(data_file) as zf:
                name = next(n for n in zf.namelist()
                            if n.endswith("ratings.dat"))
                rows = [l.split("::") for l in
                        zf.read(name).decode("latin1").splitlines() if l]
            users = np.array([int(r[0]) for r in rows], np.int64)
            movies = np.array([int(r[1]) for r in rows], np.int64)
            ratings = np.array([float(r[2]) for r in rows], np.float32)
            split_rng = np.random.RandomState(rand_seed)
            is_test = split_rng.rand(len(rows)) < test_ratio
            keep = is_test if mode == "test" else ~is_test
            self.users, self.movies, self.ratings = (
                users[keep], movies[keep], ratings[keep])
            return
        n = 2000
        self.users = rng.randint(0, 943, n).astype(np.int64)
        self.movies = rng.randint(0, 1682, n).astype(np.int64)
        self.ratings = rng.randint(1, 6, n).astype(np.float32)

    def __getitem__(self, idx):
        return (np.asarray([self.users[idx]]), np.asarray([self.movies[idx]]),
                np.asarray([self.ratings[idx]]))

    def __len__(self):
        return len(self.users)


class UCIHousing(Dataset):
    """Boston housing regression (13 features -> price)."""

    def __init__(self, data_file=None, mode="train", download=True):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            x = rng.rand(506, 13).astype(np.float32)
            w = rng.rand(13).astype(np.float32)
            y = (x @ w + 0.1 * rng.rand(506).astype(np.float32)).reshape(-1, 1)
            raw = np.concatenate([x, y], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class WMT14(_SyntheticTextDataset):
    """Machine translation: (src_ids, trg_ids, trg_next_ids).

    Real path (reference wmt14.py:107-160 parity): tar with src.dict/trg.dict
    members (one word per line, rank = id) and <mode>/<mode> members of
    tab-separated parallel lines; <s>/<e> wrapping, UNK=2, len>80 pruning."""

    VOCAB = 30000

    _MODES = ("train", "test", "gen")

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 trg_dict_size=None, download=True):
        assert mode.lower() in self._MODES, mode
        self.mode = mode.lower()
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, dict_size, trg_dict_size or dict_size)
        else:
            self.VOCAB = dict_size
            super().__init__(mode=mode, seed=300)

    def _load_real(self, data_file, dict_size, trg_dict_size):
        START, END, UNK_IDX = "<s>", "<e>", 2

        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.decode().strip()] = i
            return out

        def one_member(f, suffix):
            names = [m.name for m in f if m.name.endswith(suffix)]
            if len(names) != 1:
                raise ValueError(
                    f"{data_file}: expected exactly one *{suffix} member, "
                    f"found {names} — is this the wmt14 archive?")
            return names[0]

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file) as f:
            self.src_dict = to_dict(
                f.extractfile(one_member(f, "src.dict")), dict_size)
            self.trg_dict = to_dict(
                f.extractfile(one_member(f, "trg.dict")), trg_dict_size)
            suffix = f"{self.mode}/{self.mode}"
            members = [m.name for m in f if m.name.endswith(suffix)]
            if not members:
                raise ValueError(
                    f"{data_file}: no '{suffix}' member for mode="
                    f"'{self.mode}'")
            for name in members:
                for line in f.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, UNK_IDX)
                           for w in [START] + parts[0].split() + [END]]
                    trg = [self.trg_dict.get(w, UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.trg_ids_next.append(trg + [self.trg_dict[END]])
                    self.trg_ids.append([self.trg_dict[START]] + trg)
                    self.src_ids.append(src)

    def __getitem__(self, idx):
        if hasattr(self, "src_ids"):
            return (np.array(self.src_ids[idx], np.int64),
                    np.array(self.trg_ids[idx], np.int64),
                    np.array(self.trg_ids_next[idx], np.int64))
        row = self.data[idx]
        return row, np.roll(row, -1), np.roll(row, -2)

    def __len__(self):
        if hasattr(self, "src_ids"):
            return len(self.src_ids)
        return super().__len__()


class WMT16(WMT14):
    _MODES = ("train", "test", "val")  # reference wmt16.py accepts val

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__(data_file=data_file, mode=mode,
                         dict_size=src_dict_size,
                         trg_dict_size=trg_dict_size)


class Conll05st(_SyntheticTextDataset):
    """SRL sequence labeling: (word_ids, predicate_id, bio_label_ids).

    Real path (reference conll05.py:170-230 parity): parse the conll05st tar
    (words/*.words.gz + props/*.props.gz members; blank line = sentence end);
    bracketed-star props convert to B-/I-/O tags; one sample per (sentence,
    predicate) pair. Dicts build from the corpus unless *_dict_file given
    (one entry per line, rank = id). Returns the core (words, predicate,
    labels) triple — the reference's ctx-window/mark features derive from it."""

    VOCAB = 5000

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode="train", download=True):
        if data_file and os.path.exists(data_file):
            self._load_real(data_file, word_dict_file, verb_dict_file,
                            target_dict_file)
        else:
            super().__init__(mode=mode, seed=400)

    @staticmethod
    def _bio(lbl_cols):
        """Bracketed-star -> BIO (reference conll05.py:203-224)."""
        out, cur, inside = [], "O", False
        for l in lbl_cols:
            if l == "*" and not inside:
                out.append("O")
            elif l == "*" and inside:
                out.append("I-" + cur)
            elif l == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in l and ")" in l:
                cur = l[1:l.find("*")]
                out.append("B-" + cur)
                inside = False
            elif "(" in l:
                cur = l[1:l.find("*")]
                out.append("B-" + cur)
                inside = True
            else:
                raise RuntimeError(f"unexpected SRL label: {l}")
        return out

    def _load_real(self, data_file, word_dict_file, verb_dict_file,
                   target_dict_file):
        import gzip

        samples = []  # (words, predicate, bio_labels)

        def flush(sent, cols):
            if not (sent and cols):
                return
            verbs = [c[0] for c in cols if c[0] != "-"]
            n_pred = len(cols[0]) - 1
            for i in range(n_pred):
                samples.append((list(sent),
                                verbs[i] if i < len(verbs) else "-",
                                self._bio([c[i + 1] for c in cols])))

        with tarfile.open(data_file) as tf:
            names = tf.getnames()
            # pair words/props by shared stem — the real archive holds BOTH
            # test.wsj and test.brown trees; independent suffix picks could
            # zip one split's words against the other's props
            pairs = []
            for wn in sorted(n for n in names if n.endswith(".words.gz")):
                stem = wn.rsplit("/words/", 1)[-1][:-len(".words.gz")]
                pn = next((n for n in names
                           if n.endswith(f"/props/{stem}.props.gz")), None)
                if pn is not None:
                    pairs.append((wn, pn))
            if not pairs:
                raise ValueError(f"{data_file}: no paired words/props "
                                 "members — is this the conll05st archive?")
            for words_name, props_name in pairs:
                with gzip.GzipFile(fileobj=tf.extractfile(words_name)) as wfh, \
                        gzip.GzipFile(fileobj=tf.extractfile(props_name)) as pfh:
                    sent, cols = [], []
                    for wline, pline in zip(wfh, pfh):
                        w = wline.decode().strip()
                        p = pline.decode().strip().split()
                        if not p:  # sentence boundary
                            flush(sent, cols)
                            sent, cols = [], []
                            continue
                        sent.append(w.lower())
                        cols.append(p)
                    flush(sent, cols)  # file may lack a trailing blank line

        def read_dict(path):
            with open(path) as f:
                return {line.strip(): i for i, line in enumerate(f)
                        if line.strip()}

        def build_dict(items):
            freq = collections.Counter(items)
            return {w: i for i, (w, _) in enumerate(
                sorted(freq.items(), key=lambda x: (-x[1], x[0])))}

        self.word_dict = (read_dict(word_dict_file) if word_dict_file
                          else build_dict(w for s, _, _ in samples for w in s))
        self.predicate_dict = (read_dict(verb_dict_file) if verb_dict_file
                               else build_dict(v for _, v, _ in samples))
        self.label_dict = (read_dict(target_dict_file) if target_dict_file
                           else build_dict(l for _, _, ls in samples
                                           for l in ls))
        self.word_dict.setdefault("<unk>", len(self.word_dict))
        unk = self.word_dict["<unk>"]

        def strict(d, key, what):
            # only words get an <unk> bucket; a predicate/label missing from
            # a user-supplied dict file is a stale dict, not vocab overflow
            if key not in d:
                raise ValueError(
                    f"conll05st: {what} '{key}' not in the supplied dict "
                    "file — dict/corpus mismatch")
            return d[key]

        self.samples = [
            (np.array([self.word_dict.get(w, unk) for w in s], np.int64),
             np.array([strict(self.predicate_dict, v, "predicate")],
                      np.int64),
             np.array([strict(self.label_dict, l, "label") for l in ls],
                      np.int64))
            for s, v, ls in samples
        ]

    def get_dict(self):
        if not hasattr(self, "word_dict"):
            # synthetic fallback: shape-compatible dicts
            self.word_dict = {f"w{i}": i for i in range(self.VOCAB)}
            self.predicate_dict = {f"v{i}": i for i in range(100)}
            self.label_dict = {f"l{i}": i for i in range(20)}
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        if hasattr(self, "samples"):
            return self.samples[idx]
        # synthetic fallback emits the SAME 3-tuple shape as the real path
        row = self.data[idx]
        pred = np.array([int(row[0]) % 100], np.int64)
        labels = (row % 20).astype(np.int64)
        return row, pred, labels

    def __len__(self):
        if hasattr(self, "samples"):
            return len(self.samples)
        return super().__len__()
