"""Text datasets (python/paddle/text/datasets parity: Conll05st, Imdb, Imikolov,
Movielens, UCIHousing, WMT14, WMT16). Zero-egress: synthetic token streams with the
same sample shapes as the originals; real files are used when present on disk."""
import os

import numpy as np

from ...io.dataset import Dataset


class _SyntheticTextDataset(Dataset):
    VOCAB = 10000
    SEQ_LEN = 32
    N = 2000

    def __init__(self, mode="train", seed=0, **kwargs):
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.data = rng.randint(1, self.VOCAB, size=(self.N, self.SEQ_LEN)).astype(np.int64)
        self.labels = rng.randint(0, 2, size=self.N).astype(np.int64)

    def __getitem__(self, idx):
        return self.data[idx], np.asarray([self.labels[idx]], dtype=np.int64)

    def __len__(self):
        return self.N


class Imdb(_SyntheticTextDataset):
    """Sentiment classification: (token_ids, label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        super().__init__(mode=mode, seed=100)


class Imikolov(_SyntheticTextDataset):
    """Language-model n-grams."""

    VOCAB = 2000
    SEQ_LEN = 5

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train",
                 min_word_freq=50, download=True):
        self.SEQ_LEN = window_size
        super().__init__(mode=mode, seed=200)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", test_ratio=0.1, rand_seed=0, download=True):
        rng = np.random.RandomState(rand_seed + (0 if mode == "train" else 1))
        n = 2000
        self.users = rng.randint(0, 943, n).astype(np.int64)
        self.movies = rng.randint(0, 1682, n).astype(np.int64)
        self.ratings = rng.randint(1, 6, n).astype(np.float32)

    def __getitem__(self, idx):
        return (np.asarray([self.users[idx]]), np.asarray([self.movies[idx]]),
                np.asarray([self.ratings[idx]]))

    def __len__(self):
        return len(self.users)


class UCIHousing(Dataset):
    """Boston housing regression (13 features -> price)."""

    def __init__(self, data_file=None, mode="train", download=True):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            x = rng.rand(506, 13).astype(np.float32)
            w = rng.rand(13).astype(np.float32)
            y = (x @ w + 0.1 * rng.rand(506).astype(np.float32)).reshape(-1, 1)
            raw = np.concatenate([x, y], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class WMT14(_SyntheticTextDataset):
    """Machine translation: (src_ids, trg_ids, trg_next_ids)."""

    VOCAB = 30000

    def __init__(self, data_file=None, mode="train", dict_size=30000, download=True):
        self.VOCAB = dict_size
        super().__init__(mode=mode, seed=300)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row, np.roll(row, -1), np.roll(row, -2)


class WMT16(WMT14):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__(mode=mode, dict_size=src_dict_size)


class Conll05st(_SyntheticTextDataset):
    """SRL sequence labeling."""

    VOCAB = 5000

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode="train", download=True):
        super().__init__(mode=mode, seed=400)

    def __getitem__(self, idx):
        row = self.data[idx]
        labels = (row % 20).astype(np.int64)
        return row, labels
