"""Viterbi decoding + linear-chain CRF (reference operators/crf_decoding_op.cc,
linear_chain_crf_op.cc; 2.x API paddle.text.viterbi_decode / ViterbiDecoder).

TPU design: one lax.scan over time carrying the [B, T] score lattice (decode
keeps the [B, T] argmax backpointers per step and backtraces with a second
scan) — batch and tag dims stay vectorized, sequence lengths are masks, no
LoD. The CRF loss is fully differentiable (logsumexp forward algorithm), so
grads for emission AND transition come from XLA autodiff instead of the
reference's hand-written linear_chain_crf_grad kernel.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """potentials [B, L, T], transition [T, T], lengths [B] ->
    (scores [B], paths [B, L]).  With include_bos_eos_tag, tag T-2 is BOS
    (adds its transition row at t=0) and T-1 is EOS (added at sequence end),
    matching paddle.text.viterbi_decode."""
    pot = _t(potentials)
    trans = _t(transition_params)
    lens = _t(lengths).detach()

    def fn(pv, tv, lv):
        B, L, T = pv.shape
        lv = lv.astype(jnp.int32)
        if include_bos_eos_tag:
            init = pv[:, 0] + tv[T - 2][None, :]
        else:
            init = pv[:, 0]

        if L == 1:
            # single-step sequences: no transitions, no backtrace (a scan of
            # length 0 would index a size-0 pointer array while tracing).
            # zero-length rows mask their path to 0 like the L>1 tail mask
            score = init + (tv[:, T - 1][None, :]
                            if include_bos_eos_tag else 0.0)
            best = jnp.argmax(score, axis=1).astype(jnp.int64)
            best = jnp.where(lv > 0, best, 0)
            return jnp.max(score, axis=1), best[:, None]

        def step(carry, t):
            score = carry                                   # [B, T]
            cand = score[:, :, None] + tv[None, :, :]       # [B, from, to]
            best = jnp.max(cand, axis=1) + pv[:, t]         # [B, T]
            ptr = jnp.argmax(cand, axis=1).astype(jnp.int32)
            live = (t < lv)[:, None]
            new_score = jnp.where(live, best, score)
            # dead steps backtrace to themselves (identity pointer)
            ptr = jnp.where(live, ptr, jnp.arange(T, dtype=jnp.int32)[None, :])
            return new_score, ptr

        score, ptrs = jax.lax.scan(step, init, jnp.arange(1, L))  # ptrs [L-1, B, T]
        if include_bos_eos_tag:
            score = score + tv[:, T - 1][None, :]
        last_tag = jnp.argmax(score, axis=1).astype(jnp.int32)    # [B]
        best_score = jnp.max(score, axis=1)

        def back(carry, t):
            tag = carry                                     # [B]
            prev = jnp.take_along_axis(ptrs[t], tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, rev = jax.lax.scan(back, last_tag, jnp.arange(L - 2, -1, -1))
        path = jnp.concatenate([rev[::-1].T, last_tag[:, None]], axis=1)  # [B, L]
        # positions past each length repeat the final valid tag upstream; mask
        # them to the tag at their own position like the reference (truncated)
        pos = jnp.arange(L)[None, :]
        path = jnp.where(pos < lv[:, None], path, 0)
        return best_score, path.astype(jnp.int64)

    s, p = apply(fn, pot.detach(), trans.detach(), lens)
    s.stop_gradient = True
    p.stop_gradient = True
    return s, p


def crf_decoding(emission, transition, length=None, label=None):
    """crf_decoding_op.cc parity over the linear_chain_crf [(T+2), T]
    transition layout (row 0 start, row 1 stop, rows 2.. the [T, T] matrix).
    Returns the viterbi path [B, L] int64 (0 past each length); with `label`,
    returns per-step 0/1 correctness instead, like the reference op."""
    em = _t(emission)
    tr = _t(transition)
    B, L, T = em.shape
    if length is None:
        length = np.full((B,), L, np.int32)
    lens = _t(length).detach()

    def fn(ev, tv, lv):
        start, stop, mat = tv[0], tv[1], tv[2:]
        lv = lv.astype(jnp.int32)
        init = start[None, :] + ev[:, 0]

        if L == 1:
            best = jnp.argmax(init + stop[None, :], axis=1).astype(jnp.int64)
            return jnp.where(lv > 0, best, 0)[:, None]

        def step(carry, t):
            score = carry
            cand = score[:, :, None] + mat[None, :, :]
            best = jnp.max(cand, axis=1) + ev[:, t]
            ptr = jnp.argmax(cand, axis=1).astype(jnp.int32)
            live = (t < lv)[:, None]
            new_score = jnp.where(live, best, score)
            ptr = jnp.where(live, ptr,
                            jnp.arange(T, dtype=jnp.int32)[None, :])
            return new_score, ptr

        score, ptrs = jax.lax.scan(step, init, jnp.arange(1, L))
        score = score + stop[None, :]
        last_tag = jnp.argmax(score, axis=1).astype(jnp.int32)

        def back(carry, t):
            tag = carry
            prev = jnp.take_along_axis(ptrs[t], tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, rev = jax.lax.scan(back, last_tag, jnp.arange(L - 2, -1, -1))
        path = jnp.concatenate([rev[::-1].T, last_tag[:, None]], axis=1)
        pos = jnp.arange(L)[None, :]
        return jnp.where(pos < lv[:, None], path, 0).astype(jnp.int64)

    p = apply(fn, em.detach(), tr.detach(), lens)
    p.stop_gradient = True
    if label is not None:
        lab = _t(label).detach()
        from ..core.dispatch import apply as _apply

        ok = _apply(lambda a, b: (a == b.astype(a.dtype)).astype(jnp.int64),
                    p, lab)
        ok.stop_gradient = True
        return ok
    return p


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder parity (callable layer-style wrapper)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = _t(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def linear_chain_crf(emission, transition, label, length=None):
    """linear_chain_crf_op.cc parity, padded-batch form.

    emission [B, L, T]; transition [(T+2), T] — row 0 start weights, row 1 stop
    weights, rows 2.. the [T, T] tag-to-tag matrix (the reference's layout);
    label [B, L] int; length [B] (None = full rows). Returns per-sequence
    negative log-likelihood [B, 1] = log Z - gold score, differentiable wrt
    emission and transition.
    """
    em = _t(emission)
    tr = _t(transition)
    lab = _t(label).detach()
    B, L, T = em.shape
    if length is None:
        length = np.full((B,), L, np.int32)
    lens = _t(length).detach()

    def fn(ev, tv, yv, lv):
        start, stop, mat = tv[0], tv[1], tv[2:]
        lv = lv.astype(jnp.int32)
        yv = yv.astype(jnp.int32)
        mask = (jnp.arange(L)[None, :] < lv[:, None]).astype(ev.dtype)  # [B, L]

        # --- log partition (forward algorithm) ---
        alpha = start[None, :] + ev[:, 0]                   # [B, T]

        def fwd(carry, t):
            a = carry
            nxt = jax.nn.logsumexp(a[:, :, None] + mat[None, :, :], axis=1) + ev[:, t]
            live = (t < lv)[:, None]
            return jnp.where(live, nxt, a), None

        alpha, _ = jax.lax.scan(fwd, alpha, jnp.arange(1, L))
        logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)  # [B]

        # --- gold path score ---
        em_score = jnp.sum(
            jnp.take_along_axis(ev, yv[:, :, None], axis=2)[:, :, 0] * mask,
            axis=1)
        pair_live = mask[:, 1:]                              # [B, L-1]
        tr_score = jnp.sum(
            mat[yv[:, :-1], yv[:, 1:]] * pair_live, axis=1)
        last_idx = jnp.maximum(lv - 1, 0)
        last_tag = jnp.take_along_axis(yv, last_idx[:, None], axis=1)[:, 0]
        gold = em_score + tr_score + start[yv[:, 0]] + stop[last_tag]
        return (logz - gold)[:, None]

    return apply(fn, em, tr, lab, lens)
