"""paddle.text parity (python/paddle/text/datasets)."""
from . import datasets  # noqa: F401
from .datasets import Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st  # noqa: F401
