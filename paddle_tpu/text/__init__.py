"""paddle.text parity (python/paddle/text/datasets + viterbi/CRF ops)."""
from . import datasets  # noqa: F401
from .datasets import Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st  # noqa: F401
from .viterbi import ViterbiDecoder, linear_chain_crf, viterbi_decode  # noqa: F401
