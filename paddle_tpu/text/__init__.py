"""paddle.text parity (python/paddle/text/datasets + viterbi/CRF ops)."""
from . import datasets  # noqa: F401
from .datasets import Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st  # noqa: F401
from .viterbi import (ViterbiDecoder, crf_decoding, linear_chain_crf,  # noqa: F401
                      viterbi_decode)
