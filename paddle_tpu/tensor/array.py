"""LoDTensorArray API (fluid array_read/array_write/create_array parity).

The reference's tensor arrays back dynamic RNN state inside while_loops
(operators/array_operator.* / lod_array ops). TPU-native stance: a tensor
array is a plain python list at trace level — lax control flow carries
stacked tensors, so these exist for fluid-era API compatibility."""
import numpy as np

from ..core.tensor import Tensor


def create_array(dtype="float32", initialized_list=None):
    return list(initialized_list) if initialized_list is not None else []


def array_write(x, i, array=None):
    idx = int(np.asarray(i._data if isinstance(i, Tensor) else i))
    if array is None:
        array = []
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    idx = int(np.asarray(i._data if isinstance(i, Tensor) else i))
    return array[idx]


def array_length(array):
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.int64(len(array))))
