"""paddle.tensor API family (python/paddle/tensor/__init__.py parity)."""
from ..core.tensor import Tensor, ParamBase, to_tensor
from .to_string import set_printoptions  # noqa: F401
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import std, var, median, nanmedian, quantile, nanquantile, histogram, bincount, corrcoef, cov  # noqa: F401
from .random import *  # noqa: F401,F403
from .linalg import (  # noqa: F401
    norm, dist, cond, t, cross, cholesky, cholesky_solve, matrix_power, matrix_rank,
    det, slogdet, inv, pinv, solve, triangular_solve, lstsq, svd, qr, eig, eigh,
    eigvals, eigvalsh, lu, multi_dot, householder_product, cdist,
)
from .attribute import shape, rank, is_floating_point, is_integer, is_complex  # noqa: F401
from .array import array_length, array_read, array_write, create_array  # noqa: F401
from . import math_patch  # noqa: F401  (installs operator overloads)
