"""Random ops (python/paddle/tensor/random.py parity: rand, randn, randint, uniform,
normal, randperm, multinomial, bernoulli, poisson, standard_normal, exponential_).

TPU-native design: all draws pull explicit PRNG subkeys from the global Generator
(core/generator.py) — reference's per-device seeded Generator (framework/generator.cc)
maps onto jax.random key splitting.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.generator import default_generator
from ..core.tensor import Tensor


def _key():
    return default_generator().split()


def _d(dtype):
    d = dtype_mod.convert_dtype(dtype)
    return d if d is not None else dtype_mod.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(_key(), _shape(shape), dtype=_d(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_key(), _shape(shape), dtype=_d(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=_d(dtype), minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_key(), shp) * s + m)
    return Tensor(jax.random.normal(_key(), _shape(shape)) * std + mean)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.key(seed) if seed else _key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype=_d(dtype)) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(), _shape(shape), low, high, dtype=_d(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = dtype_mod.convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(_key(), tuple(x.shape), low, high).astype(d))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_key(), n).astype(_d(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    logits = jnp.log(jnp.clip(x._data if isinstance(x, Tensor) else jnp.asarray(x), 1e-30, None))
    key = _key()
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(num_samples,) + logits.shape[:-1] if logits.ndim > 1 else (num_samples,))
        if logits.ndim > 1:
            out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k for sampling without replacement
        g = jax.random.gumbel(key, logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    p = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(_key(), p).astype(p.dtype))


def poisson(x, name=None):
    lam = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(_key(), lam).astype(lam.dtype))


def exponential_(x, lam=1.0, name=None):
    out = jax.random.exponential(_key(), tuple(x.shape), dtype=x.dtype) / lam
    x._data = out
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = jax.random.normal(_key(), tuple(x.shape), dtype=x.dtype) * std + mean
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _key()
    x._data = jax.random.uniform(key, tuple(x.shape), dtype=x.dtype, minval=min, maxval=max)
    return x


def rand_like(x, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.uniform(_key(), tuple(x.shape), dtype=d))


def randn_like(x, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.normal(_key(), tuple(x.shape), dtype=d))
