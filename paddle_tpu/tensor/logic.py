"""Logical/comparison ops (python/paddle/tensor/logic.py parity, 9 public fns +
comparisons from operators/controlflow/compare_op.cc)."""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _cmp(fn, x, y):
    x = _t(x)
    if isinstance(y, Tensor):
        out = apply(fn, x.detach(), y.detach())
    else:
        out = apply(lambda v: fn(v, y), x.detach())
    out.stop_gradient = True
    return out


def equal(x, y, name=None):
    return _cmp(jnp.equal, x, y)


def not_equal(x, y, name=None):
    return _cmp(jnp.not_equal, x, y)


def greater_than(x, y, name=None):
    return _cmp(jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return _cmp(jnp.greater_equal, x, y)


def less_than(x, y, name=None):
    return _cmp(jnp.less, x, y)


def less_equal(x, y, name=None):
    return _cmp(jnp.less_equal, x, y)


def logical_and(x, y, name=None, out=None):
    return _cmp(jnp.logical_and, x, y)


def logical_or(x, y, name=None, out=None):
    return _cmp(jnp.logical_or, x, y)


def logical_xor(x, y, name=None, out=None):
    return _cmp(jnp.logical_xor, x, y)


def logical_not(x, name=None, out=None):
    return _cmp(lambda v, _=None: jnp.logical_not(v), x, None)


def bitwise_and(x, y, name=None):
    return _cmp(jnp.bitwise_and, x, y)


def bitwise_or(x, y, name=None):
    return _cmp(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, name=None):
    return _cmp(jnp.bitwise_xor, x, y)


def bitwise_not(x, name=None):
    return _cmp(lambda v, _=None: jnp.bitwise_not(v), x, None)


def equal_all(x, y, name=None):
    return _cmp(lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _cmp(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _cmp(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_t(x).size == 0))
