"""Statistics ops (python/paddle/tensor/stat.py parity: mean, std, var, median,
nanmedian, quantile, nanquantile)."""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .math import mean  # re-export


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        _t(x),
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(
        lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        _t(x),
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(v):
        if mode == "min":
            # paddle 'min' mode: lower of the two middle values
            n = v.shape[_axis(axis)] if axis is not None else v.size
            sorted_v = jnp.sort(v.reshape(-1) if axis is None else v, axis=-1 if axis is None else _axis(axis))
            k = (n - 1) // 2
            return jnp.take(sorted_v, k, axis=-1 if axis is None else _axis(axis))
        return jnp.median(v, axis=_axis(axis), keepdims=keepdim)

    return apply(fn, _t(x))


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._data if isinstance(q, Tensor) else q
    return apply(
        lambda v: jnp.quantile(v, jnp.asarray(qv), axis=_axis(axis), keepdims=keepdim, method=interpolation),
        _t(x),
    )


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._data if isinstance(q, Tensor) else q
    return apply(
        lambda v: jnp.nanquantile(v, jnp.asarray(qv), axis=_axis(axis), keepdims=keepdim, method=interpolation),
        _t(x),
    )


def histogram(input, bins=100, min=0, max=0, name=None):
    x = np.asarray(_t(input)._data)
    lo, hi = (min, max) if (min != 0 or max != 0) else (x.min(), x.max())
    hist, _ = np.histogram(x, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(hist.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    w = np.asarray(weights._data) if isinstance(weights, Tensor) else weights
    out = np.bincount(np.asarray(_t(x)._data), weights=w, minlength=minlength)
    return Tensor(jnp.asarray(out))


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda v: jnp.corrcoef(v, rowvar=rowvar), _t(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), _t(x))
