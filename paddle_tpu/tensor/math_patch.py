"""Operator overloads + method attachment for Tensor.

Reference parity: python/paddle/fluid/dygraph/math_op_patch.py (monkey-patched dunder ops)
and varbase_patch_methods.py (Tensor methods delegating to the functional API).
"""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from . import creation, linalg, logic, manipulation, math, random, search, stat


def _scalar_or_tensor(fn_tensor, fn_scalar):
    def op(self, other):
        if isinstance(other, Tensor):
            return fn_tensor(self, other)
        if isinstance(other, (list, tuple, np.ndarray)):
            return fn_tensor(self, Tensor(np.asarray(other)))
        return fn_scalar(self, other)

    return op


def _install():
    T = Tensor

    T.__add__ = _scalar_or_tensor(math.add, lambda s, o: apply(lambda v: v + o, s))
    T.__radd__ = T.__add__
    T.__sub__ = _scalar_or_tensor(math.subtract, lambda s, o: apply(lambda v: v - o, s))
    T.__rsub__ = _scalar_or_tensor(
        lambda s, o: math.subtract(o, s), lambda s, o: apply(lambda v: o - v, s)
    )
    T.__mul__ = _scalar_or_tensor(math.multiply, lambda s, o: apply(lambda v: v * o, s))
    T.__rmul__ = T.__mul__
    T.__truediv__ = _scalar_or_tensor(math.divide, lambda s, o: apply(lambda v: v / o, s))
    T.__rtruediv__ = _scalar_or_tensor(
        lambda s, o: math.divide(o, s), lambda s, o: apply(lambda v: o / v, s)
    )
    T.__floordiv__ = _scalar_or_tensor(
        math.floor_divide, lambda s, o: apply(lambda v: jnp.floor_divide(v, o), s)
    )
    T.__mod__ = _scalar_or_tensor(math.mod, lambda s, o: apply(lambda v: jnp.mod(v, o), s))
    T.__pow__ = _scalar_or_tensor(math.pow, lambda s, o: apply(lambda v: jnp.power(v, o), s))
    T.__rpow__ = _scalar_or_tensor(
        lambda s, o: math.pow(o, s), lambda s, o: apply(lambda v: jnp.power(o, v), s)
    )
    T.__neg__ = lambda self: apply(jnp.negative, self)
    T.__abs__ = lambda self: apply(jnp.abs, self)
    T.__matmul__ = lambda self, other: math.matmul(self, other)
    T.__rmatmul__ = lambda self, other: math.matmul(other, self)
    T.__invert__ = lambda self: logic.logical_not(self) if self.dtype == np.dtype("bool") else logic.bitwise_not(self)
    T.__and__ = _scalar_or_tensor(
        lambda s, o: logic.logical_and(s, o) if s.dtype == np.dtype("bool") else logic.bitwise_and(s, o),
        lambda s, o: apply(lambda v: v & o, s),
    )
    T.__or__ = _scalar_or_tensor(
        lambda s, o: logic.logical_or(s, o) if s.dtype == np.dtype("bool") else logic.bitwise_or(s, o),
        lambda s, o: apply(lambda v: v | o, s),
    )
    T.__xor__ = _scalar_or_tensor(
        lambda s, o: logic.logical_xor(s, o) if s.dtype == np.dtype("bool") else logic.bitwise_xor(s, o),
        lambda s, o: apply(lambda v: v ^ o, s),
    )
    def _eq(self, other):
        if other is None:
            return False
        return _scalar_or_tensor(logic.equal, lambda s, o: logic.equal(s, o))(self, other)

    def _ne(self, other):
        if other is None:
            return True
        return _scalar_or_tensor(logic.not_equal, lambda s, o: logic.not_equal(s, o))(self, other)

    T.__eq__ = _eq
    T.__ne__ = _ne
    T.__lt__ = _scalar_or_tensor(logic.less_than, lambda s, o: logic.less_than(s, o))
    T.__le__ = _scalar_or_tensor(logic.less_equal, lambda s, o: logic.less_equal(s, o))
    T.__gt__ = _scalar_or_tensor(logic.greater_than, lambda s, o: logic.greater_than(s, o))
    T.__ge__ = _scalar_or_tensor(logic.greater_equal, lambda s, o: logic.greater_equal(s, o))

    # methods: every tensor.* function becomes a Tensor method (varbase_patch parity)
    families = [math, manipulation, linalg, logic, search, stat, creation]
    skip = {"to_tensor", "ones", "zeros", "full", "arange", "eye", "linspace", "logspace",
            "empty", "meshgrid", "assign"}
    for mod in families:
        for name in dir(mod):
            if name.startswith("_") or name in skip:
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            if not hasattr(T, name):
                setattr(T, name, fn)

    # special-cased methods
    T.mean = math.mean
    T.sum = math.sum
    T.max = math.max
    T.min = math.min
    T.abs = math.abs
    T.exp = math.exp
    T.log = math.log
    T.sqrt = math.sqrt
    T.matmul = math.matmul
    T.reshape = manipulation.reshape
    T.transpose = manipulation.transpose
    T.flatten = manipulation.flatten
    T.squeeze = manipulation.squeeze
    T.unsqueeze = manipulation.unsqueeze
    T.argmax = search.argmax
    T.argmin = search.argmin
    T.topk = search.topk
    T.cast = lambda self, dtype: self.astype(dtype)


_install()
