"""Linear algebra ops (python/paddle/tensor/linalg.py parity: norm, dist, cond, matrix_*,
svd, qr, eig/eigh, cholesky, solve family, pinv, det, slogdet, lu, lstsq).

TPU note: decompositions (svd/qr/eig) run on XLA's CPU path when not supported on-device;
matmul-heavy ops (norm, matrix_power) stay on the MXU.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .math import matmul, dot, bmm, mv, einsum  # re-export


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def fn(v):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(v * v))
            return jnp.linalg.norm(v, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=_ax(axis), keepdims=keepdim)
        if p == np.inf or p == float("inf"):
            if axis is None:
                return jnp.max(jnp.abs(v))
            return jnp.linalg.norm(v, ord=np.inf, axis=_ax(axis), keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            if axis is None:
                return jnp.min(jnp.abs(v))
            return jnp.linalg.norm(v, ord=-np.inf, axis=_ax(axis), keepdims=keepdim)
        if axis is None:
            return jnp.sum(jnp.abs(v) ** p) ** (1.0 / p)
        return jnp.linalg.norm(v, ord=p, axis=_ax(axis), keepdims=keepdim)

    def _ax(a):
        if a is None:
            return None
        if isinstance(a, (list, tuple)):
            return tuple(a)
        return int(a)

    return apply(fn, _t(x))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply(lambda v: jnp.linalg.norm(v, ord=None if p == "fro" else p, axis=tuple(axis), keepdims=keepdim), _t(x))


def dist(x, y, p=2, name=None):
    return apply(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), _t(x), _t(y))


def cond(x, p=None, name=None):
    return apply(lambda v: jnp.linalg.cond(v, p=p), _t(x))


def t(x, name=None):
    return apply(lambda v: jnp.swapaxes(v, -1, -2) if v.ndim >= 2 else v, _t(x))


def transpose(x, perm, name=None):
    from .manipulation import transpose as _tr

    return _tr(x, perm)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply(fn, _t(x), _t(y))


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l

    return apply(fn, _t(x))


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)

    return apply(fn, _t(x), _t(y))


def matrix_power(x, n, name=None):
    return apply(lambda v: jnp.linalg.matrix_power(v, n), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    tv = tol._data if isinstance(tol, Tensor) else tol
    out = apply(lambda v: jnp.linalg.matrix_rank(v, rtol=None if tv is None else tv), _t(x).detach())
    out.stop_gradient = True
    return out


def det(x, name=None):
    return apply(jnp.linalg.det, _t(x))


def slogdet(x, name=None):
    sign, logdet = apply(lambda v: tuple(jnp.linalg.slogdet(v)), _t(x))
    return sign, logdet


def inv(x, name=None):
    return apply(jnp.linalg.inv, _t(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), _t(x))


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, _t(x), _t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply(fn, _t(x), _t(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = apply(lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), _t(x), _t(y))
    return sol, res, rank, sv


def svd(x, full_matrices=False, name=None):
    u, s, vh = apply(lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), _t(x))
    # paddle returns V, not V^H
    from .manipulation import transpose as _tr

    v = apply(lambda m: jnp.swapaxes(m, -1, -2).conj(), vh)
    return u, s, v


def qr(x, mode="reduced", name=None):
    if mode == "r":
        return apply(lambda v: jnp.linalg.qr(v, mode="r"), _t(x))
    q, r = apply(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), _t(x))
    return q, r


def eig(x, name=None):
    w, v = apply(lambda m: tuple(jnp.linalg.eig(m)), _t(x).detach())
    return w, v


def eigh(x, UPLO="L", name=None):
    w, v = apply(lambda m: tuple(jnp.linalg.eigh(m, UPLO=UPLO)), _t(x))
    return w, v


def eigvals(x, name=None):
    out = apply(jnp.linalg.eigvals, _t(x).detach())
    return out


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda m: jnp.linalg.eigvalsh(m, UPLO=UPLO), _t(x))


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based

    lu_t, piv = apply(fn, _t(x))
    piv.stop_gradient = True
    if get_infos:
        info = Tensor(jnp.zeros((), dtype=jnp.int32))
        return lu_t, piv, info
    return lu_t, piv


def multi_dot(x, name=None):
    tensors = [_t(v) for v in x]
    return apply(lambda *vs: jnp.linalg.multi_dot(vs), *tensors)


def householder_product(x, tau, name=None):
    def fn(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype), a[i + 1 :, i]])
            q = q - t_[i] * jnp.outer(q @ v, v)
        return q

    return apply(fn, _t(x), _t(tau))


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    """Parity: paddle.cdist — pairwise p-norm distance [.., M, N]."""
    def fn(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 0.0:  # hamming-style count of differing components
            return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
        if p == 2.0:
            d2 = jnp.sum(diff * diff, axis=-1)
            # masked sqrt: sqrt'(0)=inf would NaN the gradient of every
            # zero-distance pair (cdist(x, x)'s whole diagonal)
            return jnp.where(d2 == 0, 0.0, jnp.sqrt(jnp.where(d2 == 0, 1.0, d2)))
        if p == float("inf"):
            return jnp.max(jnp.abs(diff), axis=-1)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return apply(fn, _t(x), _t(y))
