"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py (17 public fns: to_tensor, ones, zeros,
full, arange, eye, linspace, empty, *_like, tril/triu, meshgrid, diag, assign, ...).
"""
import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.dispatch import apply
from ..core.tensor import Tensor, to_tensor  # re-export


def _d(dtype, like=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = like if like is not None else dtype_mod.get_default_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), dtype=_d(dtype)))


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), dtype=_d(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        if isinstance(fill_value, bool):
            d = np.dtype("bool")
        elif isinstance(fill_value, int):
            d = dtype_mod.get_default_dtype()
        else:
            d = dtype_mod.get_default_dtype()
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=d))


def ones_like(x, dtype=None, name=None):
    return apply(lambda v: jnp.ones_like(v, dtype=dtype_mod.convert_dtype(dtype)), x)


def zeros_like(x, dtype=None, name=None):
    return apply(lambda v: jnp.zeros_like(v, dtype=dtype_mod.convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply(
        lambda v: jnp.full_like(v, fill_value, dtype=dtype_mod.convert_dtype(dtype)), x
    )


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            d = np.dtype("int64")
        else:
            d = dtype_mod.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=d))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_d(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=_d(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base), dtype=_d(dtype)))


def tril(x, diagonal=0, name=None):
    return apply(lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply(lambda v: jnp.triu(v, k=diagonal), x)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *args)
    return list(outs)


def diag(x, offset=0, padding_value=0, name=None):
    def _diag(v):
        out = jnp.diag(v, k=offset)
        if v.ndim == 1 and padding_value != 0:
            mask = jnp.diag(jnp.ones_like(v, dtype=bool), k=offset)
            out = jnp.where(mask, out, jnp.asarray(padding_value, dtype=v.dtype))
        return out

    return apply(_diag, x)


def diagflat(x, offset=0, name=None):
    return apply(lambda v: jnp.diagflat(v, k=offset), x)


def assign(x, output=None):
    """python/paddle/tensor/creation.py assign parity."""
    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    out = apply(lambda v: v + jnp.zeros_like(v), x)
    if output is not None:
        output._data = out._data
        output._node = out._node
        return output
    return out


def clone(x, name=None):
    return assign(x)


def complex(real, imag, name=None):
    return apply(lambda r, i: r + 1j * i.astype(jnp.complex64 if r.dtype == jnp.float32 else jnp.complex128), real, imag)


def as_complex(x, name=None):
    return apply(lambda v: v[..., 0] + 1j * v[..., 1], x)


def as_real(x, name=None):
    return apply(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def _batch_size_like_shape(input, shape, input_dim_idx, output_dim_idx):
    """Shared *_batch_size_like shape builder: copy the input's batch dim."""
    ref = input if hasattr(input, "shape") else Tensor(jnp.asarray(input))
    shape = list(shape)
    shape[output_dim_idx] = ref.shape[input_dim_idx]
    return shape


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    """fill_constant_batch_size_like_op.cc parity: like full(shape) but the
    output's batch dim copies the input's (dynamic RNN init-state idiom)."""
    shape = _batch_size_like_shape(input, shape, input_dim_idx, output_dim_idx)
    return full(shape, value, dtype=dtype)


def uniform_random_batch_size_like(input, shape, low=-1.0, high=1.0,
                                   input_dim_idx=0, output_dim_idx=0,
                                   dtype="float32", name=None):
    from .random import uniform

    shape = _batch_size_like_shape(input, shape, input_dim_idx, output_dim_idx)
    return uniform(shape, min=low, max=high, dtype=dtype)


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    dtype="float32", name=None):
    from .random import normal

    shape = _batch_size_like_shape(input, shape, input_dim_idx, output_dim_idx)
    out = normal(mean=mean, std=std, shape=shape)
    return out.astype(dtype) if dtype not in (None, "float32") else out
