"""Shape / layout manipulation ops.

Reference parity: python/paddle/tensor/manipulation.py (29 public fns: reshape, transpose,
concat, split, stack, squeeze, gather, scatter, tile, flip, roll, ...). Static shapes
only — XLA requirement; dynamic-shape paddle APIs (e.g. masked_select) return compacted
results eagerly or require a size hint under jit.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply, apply_inplace
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _int_list(xs):
    if isinstance(xs, Tensor):
        return [int(v) for v in xs.numpy()]
    if isinstance(xs, (int, np.integer)):
        return [int(xs)]
    return [int(x._data) if isinstance(x, Tensor) else int(x) for x in xs]


def reshape(x, shape, name=None):
    return apply(lambda v: jnp.reshape(v, tuple(_int_list(shape))), _t(x))


def reshape_(x, shape, name=None):
    return apply_inplace(lambda v: jnp.reshape(v, tuple(_int_list(shape))), x)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1 :]
        return jnp.reshape(v, new_shape)

    return apply(fn, _t(x))


def transpose(x, perm=None, name=None):
    return apply(lambda v: jnp.transpose(v, None if perm is None else tuple(perm)), _t(x))


def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), _t(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda v: jnp.swapaxes(v, axis0, axis1), _t(x))


transpose_ = transpose


def unsqueeze(x, axis, name=None):
    axes = _int_list(axis)

    def fn(v):
        out = v
        for a in sorted(axes):
            out = jnp.expand_dims(out, a)
        return out

    return apply(fn, _t(x))


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = tuple(a % v.ndim for a in _int_list(axis))
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return apply(fn, _t(x))


unsqueeze_ = unsqueeze
squeeze_ = squeeze


def concat(x, axis=0, name=None):
    tensors = [_t(v) for v in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda *vs: jnp.concatenate(vs, axis=axis), *tensors)


def stack(x, axis=0, name=None):
    tensors = [_t(v) for v in x]
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *tensors)


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else _t(x).shape[axis]
    outs = apply(
        lambda v: tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis)),
        _t(x),
    )
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = _int_list(num_or_sections)
        if any(s == -1 for s in sections):
            known = sum(s for s in sections if s != -1)
            sections = [dim - known if s == -1 else s for s in sections]
    offsets = np.cumsum(sections)[:-1].tolist()
    outs = apply(lambda v: tuple(jnp.split(v, offsets, axis=axis)), x)
    return list(outs) if isinstance(outs, tuple) else [outs]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    return apply(lambda v: jnp.tile(v, tuple(_int_list(repeat_times))), _t(x))


def expand(x, shape, name=None):
    shape = _int_list(shape)

    def fn(v):
        tgt = list(shape)
        for i in range(1, len(tgt) + 1):
            if i <= v.ndim and tgt[-i] == -1:
                tgt[-i] = v.shape[-i]
        return jnp.broadcast_to(v, tuple(tgt))

    return apply(fn, _t(x))


def expand_as(x, y, name=None):
    return apply(lambda v, w: jnp.broadcast_to(v, w.shape), _t(x), _t(y).detach())


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    tensors = [_t(v) for v in inputs]
    outs = apply(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *tensors)
    return list(outs)


def flip(x, axis, name=None):
    return apply(lambda v: jnp.flip(v, axis=tuple(_int_list(axis))), _t(x))


def roll(x, shifts, axis=None, name=None):
    sh = _int_list(shifts)
    ax = None if axis is None else _int_list(axis)

    def fn(v):
        if ax is None:
            return jnp.roll(v, sh[0] if len(sh) == 1 else tuple(sh))
        return jnp.roll(v, tuple(sh), axis=tuple(ax))

    return apply(fn, _t(x))


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis), _t(x), _t(index).detach())


def gather_nd(x, index, name=None):
    def fn(v, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat_idx = tuple(idx[..., j] for j in range(k))
        return v[flat_idx]

    return apply(fn, _t(x), _t(index).detach())


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(
        lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=axis),
        _t(arr),
        _t(indices).detach(),
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(v, i, val):
        i = i.astype(jnp.int32)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, val, axis=axis, inplace=False)
        if reduce == "add":
            dims = list(range(v.ndim))
            # scatter-add via segment trick: use at[] with explicit index grids
            idx = [jnp.broadcast_to(jnp.arange(s).reshape([-1 if d == j else 1 for d in dims]), i.shape) for j, s in enumerate(v.shape)]
            idx[axis] = i
            return v.at[tuple(idx)].add(jnp.broadcast_to(val, i.shape))
        raise ValueError(reduce)

    return apply(fn, _t(arr), _t(indices).detach(), _t(values))


def scatter(x, index, updates, overwrite=True, name=None):
    """operators/scatter_op.cc parity: row-wise scatter on axis 0."""

    def fn(v, i, u):
        i = i.reshape(-1).astype(jnp.int32)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].set(jnp.zeros_like(u)).at[i].add(u)

    return apply(fn, _t(x), _t(index).detach(), _t(updates))


def scatter_(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.reshape(-1).astype(jnp.int32)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].set(jnp.zeros_like(u)).at[i].add(u)

    return apply_inplace(fn, x, _t(index).detach(), _t(updates))


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, i, u):
        i = i.astype(jnp.int32)
        k = i.shape[-1]
        return v.at[tuple(i[..., j] for j in range(k))].add(u)

    return apply(fn, _t(x), _t(index).detach(), _t(updates))


def scatter_nd(index, updates, shape, name=None):
    z = Tensor(jnp.zeros(tuple(_int_list(shape)), dtype=_t(updates).dtype))
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    return take_along_axis(x, index, axis=1)


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (uses concrete mask)
    x, mask = _t(x), _t(mask)
    sel = np.asarray(mask._data)
    return apply(lambda v: v[jnp.asarray(np.nonzero(sel.reshape(-1))[0])], reshape(x, [-1]))


def masked_fill(x, mask, value, name=None):
    v = value._data if isinstance(value, Tensor) else value
    return apply(lambda a, m: jnp.where(m, jnp.asarray(v, dtype=a.dtype), a), _t(x), _t(mask).detach())


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply(lambda c, a, b: jnp.where(c, a, b), _t(condition).detach(), _t(x), _t(y))


def nonzero(x, as_tuple=False):
    x = _t(x)
    nz = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = _t(x)
    res = np.unique(
        np.asarray(x._data),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = np.asarray(_t(x)._data)
    if axis is None:
        x = x.reshape(-1)
    keep = np.ones(x.shape[0], dtype=bool)
    keep[1:] = (x[1:] != x[:-1]).reshape(x.shape[0] - 1, -1).any(axis=-1) if x.ndim > 1 else x[1:] != x[:-1]
    out = Tensor(jnp.asarray(x[keep]))
    outs = [out]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, x.shape[0]))
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def slice(input, axes, starts, ends, name=None):
    import builtins

    axes = _int_list(axes)
    starts = _int_list(starts)
    ends = _int_list(ends)

    def fn(v):
        idx = [builtins.slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins.slice(s, e)
        return v[tuple(idx)]

    return apply(fn, _t(input))


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins

    axes, starts, ends, strides = map(_int_list, (axes, starts, ends, strides))

    def fn(v):
        idx = [builtins.slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins.slice(s, e, st)
        return v[tuple(idx)]

    return apply(fn, _t(x))


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    shape = _int_list(shape)
    offsets = _int_list(offsets) if offsets is not None else [0] * len(shape)

    def fn(v):
        idx = tuple(
            builtins.slice(o, o + (s if s != -1 else v.shape[d] - o))
            for d, (o, s) in enumerate(zip(offsets, shape))
        )
        return v[idx]

    return apply(fn, _t(x))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn.functional.common import pad as _pad

    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply(lambda v: jnp.repeat(v, r, axis=axis), _t(x))


def as_strided(x, shape, stride, offset=0, name=None):
    def fn(v):
        flat = v.reshape(-1)[offset:]
        idx = np.zeros(tuple(shape), dtype=np.int64)
        for d, (s, st) in enumerate(zip(shape, stride)):
            rng = np.arange(s) * st
            idx = idx + rng.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]

    return apply(fn, _t(x))


def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), _t(x), _t(y))


def tolist(x):
    return _t(x).tolist()


def numel(x, name=None):
    return Tensor(jnp.asarray(_t(x).size, dtype=jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """operators/shard_index_op.cc parity (PS embedding sharding)."""
    shard_size = (index_num + nshards - 1) // nshards

    def fn(v):
        in_shard = (v // shard_size) == shard_id
        return jnp.where(in_shard, v % shard_size, ignore_value)

    return apply(fn, _t(input))


def unbind(input, axis=0, name=None):
    """Parity: paddle.unbind — split along `axis` into axis-size tensors.
    ONE multi-output op (single tape node/vjp), not N slices."""
    x = _t(input)
    n = x.shape[axis]
    return apply(
        lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)), x)


def cast(x, dtype):
    """Parity: paddle.cast (cast_op.cc) — delegates to Tensor.astype (same
    dispatch + autograd path)."""
    return _t(x).astype(dtype)


def reverse(x, axis, name=None):
    """fluid.layers.reverse parity (reverse_op.cc) — alias of flip."""
    return flip(x, axis)


def _index_add_fn(axis):
    def fn(xv, iv, vv):
        perm = None
        if axis % xv.ndim != 0:
            perm = list(range(xv.ndim))
            perm[0], perm[axis] = perm[axis], perm[0]
            xv = jnp.transpose(xv, perm)
            vv = jnp.transpose(vv, perm)
        out = xv.at[iv.astype(jnp.int32)].add(vv)
        if perm is not None:
            out = jnp.transpose(out, perm)
        return out

    return fn


def index_add(x, index, axis, value, name=None):
    """index_add_op parity: x with value rows scatter-added at `index` along
    `axis` (XLA scatter-add; duplicate indices accumulate)."""
    return apply(_index_add_fn(axis), _t(x), _t(index).detach(), _t(value))


def index_add_(x, index, axis, value, name=None):
    return apply_inplace(_index_add_fn(axis), _t(x), _t(index).detach(),
                         _t(value))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    """diag_embed_op parity (same impl as nn.functional.extension.diag_embed,
    exported at paddle.* level like the reference)."""
    from ..nn.functional.extension import diag_embed as _de

    return _de(input, offset=offset, dim1=dim1, dim2=dim2)


def unfold(x, axis, size, step, name=None):
    """Tensor.unfold parity (sliding windows along `axis`): returns a view-like
    tensor with a trailing window dim of `size`, windows spaced by `step`."""
    def fn(v):
        ax = axis % v.ndim
        n = (v.shape[ax] - size) // step + 1
        starts = jnp.arange(n) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]       # [n, size]
        win = jnp.take(v, idx.reshape(-1), axis=ax)
        shp = list(v.shape)
        shp[ax:ax + 1] = [n, size]
        win = win.reshape(shp)
        # paddle puts the window dim last
        perm = list(range(len(shp)))
        perm.append(perm.pop(ax + 1))
        return jnp.transpose(win, perm)

    return apply(fn, _t(x))
