"""Elementwise + reduction math ops.

Reference parity: python/paddle/tensor/math.py (41 public fns) backed by
paddle/fluid/operators/elementwise/ and reduce_ops/. All ops are thin pure-jnp lambdas
through the autodiff dispatcher; XLA fuses chains of these into single kernels, replacing
the reference's fused_elemwise_activation op (operators/fused/).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.dispatch import apply, apply_inplace
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _binop(fn, x, y, name=None):
    x = _t(x)
    # python scalars stay scalars (no dtype promotion surprises)
    if isinstance(y, Tensor):
        return apply(fn, x, y)
    return apply(lambda v: fn(v, y), x)


# ---- elementwise binary ------------------------------------------------------
def add(x, y, name=None):
    return _binop(jnp.add, x, y)


def subtract(x, y, name=None):
    return _binop(jnp.subtract, x, y)


def multiply(x, y, name=None):
    return _binop(jnp.multiply, x, y)


def divide(x, y, name=None):
    return _binop(jnp.divide, x, y)


def floor_divide(x, y, name=None):
    return _binop(jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return _binop(jnp.mod, x, y)


remainder = mod
floor_mod = mod


def pow(x, y, name=None):
    return _binop(jnp.power, x, y)


def maximum(x, y, name=None):
    return _binop(jnp.maximum, x, y)


def minimum(x, y, name=None):
    return _binop(jnp.minimum, x, y)


def fmax(x, y, name=None):
    return _binop(jnp.fmax, x, y)


def fmin(x, y, name=None):
    return _binop(jnp.fmin, x, y)


def atan2(x, y, name=None):
    return _binop(jnp.arctan2, x, y)


def hypot(x, y, name=None):
    return _binop(jnp.hypot, x, y)


# ---- elementwise unary -------------------------------------------------------
def _unary(fn):
    def op(x, name=None):
        return apply(fn, _t(x))

    return op


exp = _unary(jnp.exp)
expm1 = _unary(jnp.expm1)
log = _unary(jnp.log)
log2 = _unary(jnp.log2)
log10 = _unary(jnp.log10)
log1p = _unary(jnp.log1p)
sqrt = _unary(jnp.sqrt)
rsqrt = _unary(lambda v: jax.lax.rsqrt(v))
square = _unary(jnp.square)
sin = _unary(jnp.sin)
cos = _unary(jnp.cos)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
acos = _unary(jnp.arccos)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
cosh = _unary(jnp.cosh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
acosh = _unary(jnp.arccosh)
atanh = _unary(jnp.arctanh)
abs = _unary(jnp.abs)
ceil = _unary(jnp.ceil)
floor = _unary(jnp.floor)
round = _unary(jnp.round)
trunc = _unary(jnp.trunc)
frac = _unary(lambda v: v - jnp.trunc(v))
sign = _unary(jnp.sign)
neg = _unary(jnp.negative)
reciprocal = _unary(jnp.reciprocal)
sigmoid = _unary(jax.nn.sigmoid)
erf = _unary(jax.scipy.special.erf)
erfinv = _unary(jax.scipy.special.erfinv)
lgamma = _unary(jax.scipy.special.gammaln)
digamma = _unary(jax.scipy.special.digamma)
angle = _unary(jnp.angle)
conj = _unary(jnp.conj)
real = _unary(jnp.real)
imag = _unary(jnp.imag)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
isnan_ = _unary(jnp.isnan)
logit = _unary(jax.scipy.special.logit)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """operators/scale_op.cc parity."""
    def fn(v):
        s = jnp.asarray(scale._data if isinstance(scale, Tensor) else scale, dtype=v.dtype)
        b = jnp.asarray(bias, dtype=v.dtype)
        out = v * s + b if bias_after_scale else (v + b) * s
        return out

    return apply(fn, _t(x))


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply(lambda v: jnp.clip(v, lo, hi), _t(x))


def increment(x, value=1.0, name=None):
    return apply_inplace(lambda v: v + jnp.asarray(value, dtype=v.dtype), x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), _t(x))


def multiplex(inputs, index, name=None):
    """operators/multiplex_op.cc parity: out[b] = inputs[index[b]][b]."""

    def fn(*vs):
        idx = vs[-1]
        stacked = jnp.stack(vs[:-1], axis=0)  # [n, batch, ...]
        sel = idx.reshape(-1).astype(jnp.int32)
        sel = sel.reshape((1, -1) + (1,) * (stacked.ndim - 2))
        sel = jnp.broadcast_to(sel, (1,) + stacked.shape[1:])
        return jnp.take_along_axis(stacked, sel, axis=0)[0]

    return apply(fn, *inputs, _t(index))


# ---- reductions --------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda v: jnp.sum(v, axis=_axis(axis), dtype=d, keepdims=keepdim), _t(x))


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda v: jnp.prod(v, axis=_axis(axis), dtype=d, keepdims=keepdim), _t(x))


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(
        lambda v: jax.scipy.special.logsumexp(v, axis=_axis(axis), keepdims=keepdim), _t(x)
    )


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), _t(x))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda v: jnp.nansum(v, axis=_axis(axis), dtype=d, keepdims=keepdim), _t(x))


def cumsum(x, axis=None, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=d)
        return jnp.cumsum(v, axis=int(axis), dtype=d)

    return apply(fn, _t(x))


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype)
    return apply(lambda v: jnp.cumprod(v, axis=dim, dtype=d), _t(x))


def cummax(x, axis=None, dtype="int64", name=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        out = jax.lax.associative_scan(jnp.maximum, vv, axis=a)
        idx = jnp.argmax(
            jnp.cumsum(jnp.ones_like(vv, dtype=jnp.int32), axis=a) * (vv == out), axis=a
        )
        return out

    return apply(fn, _t(x))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), _t(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), _t(x))


def kron(x, y, name=None):
    return apply(jnp.kron, _t(x), _t(y))


def gcd(x, y, name=None):
    return _binop(jnp.gcd, x, y)


def lcm(x, y, name=None):
    return _binop(jnp.lcm, x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._data if isinstance(prepend, Tensor) else prepend
    app = append._data if isinstance(append, Tensor) else append
    return apply(lambda v: jnp.diff(v, n=n, axis=axis, prepend=pre, append=app), _t(x))


def heaviside(x, y, name=None):
    return _binop(jnp.heaviside, x, y)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), _t(x))


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), _t(x), _t(y), weight)
    return apply(lambda a, b: a + weight * (b - a), _t(x), _t(y))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), _t(x))


# ---- matmul family (the MXU path) -------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """operators/matmul_v2_op.cc parity. bf16-preserving; feeds the MXU directly."""

    def fn(a, b):
        from ..amp.auto_cast import amp_dtype

        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        d = amp_dtype()
        if d is not None and jnp.issubdtype(a.dtype, jnp.floating):
            a, b = a.astype(d), b.astype(d)
        return jnp.matmul(a, b)

    return apply(fn, _t(x), _t(y))


def mm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y))


def bmm(x, y, name=None):
    return apply(jnp.matmul, _t(x), _t(y))


def inner(x, y, name=None):
    return apply(jnp.inner, _t(x), _t(y))


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), _t(x), _t(y))


def mv(x, vec, name=None):
    return apply(jnp.matmul, _t(x), _t(vec))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), _t(input), _t(x), _t(y))


def inverse(x, name=None):
    return apply(jnp.linalg.inv, _t(x))


def einsum(equation, *operands):
    ops = [_t(o) for o in operands]
    return apply(lambda *vs: jnp.einsum(equation, *vs), *ops)


def isfinite(x, name=None):
    return apply(jnp.isfinite, _t(x))


def isinf(x, name=None):
    return apply(jnp.isinf, _t(x))


def isnan(x, name=None):
    return apply(jnp.isnan, _t(x))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---- in-place variants -------------------------------------------------------
def add_(x, y, name=None):
    yv = y._data if isinstance(y, Tensor) else y
    return apply_inplace(lambda v: v + yv, x) if not isinstance(y, Tensor) else apply_inplace(jnp.add, x, y)


def subtract_(x, y, name=None):
    return apply_inplace(jnp.subtract, x, _t(y))


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def fn(v):
        s = jnp.asarray(scale, dtype=v.dtype)
        b = jnp.asarray(bias, dtype=v.dtype)
        return v * s + b if bias_after_scale else (v + b) * s

    return apply_inplace(fn, x)


def clip_(x, min=None, max=None, name=None):
    return apply_inplace(lambda v: jnp.clip(v, min, max), x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal rule integration. Parity: paddle.trapezoid (reference
    python/paddle/tensor/math.py trapezoid family)."""
    y = _t(y)
    if x is not None and dx is not None:
        raise ValueError("trapezoid: pass either x or dx, not both")
    if x is not None:
        return apply(lambda yv, xv: jnp.trapezoid(yv, x=xv, axis=axis), y, _t(x))
    step = 1.0 if dx is None else dx
    return apply(lambda yv: jnp.trapezoid(yv, dx=step, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = _t(y)
    if x is not None and dx is not None:
        raise ValueError("cumulative_trapezoid: pass either x or dx, not both")

    def _cumtrap(yv, xv=None):
        y1 = jnp.moveaxis(yv, axis, -1)
        heights = (y1[..., 1:] + y1[..., :-1]) / 2.0
        if xv is None:
            widths = dx if dx is not None else 1.0
            areas = heights * widths
        else:
            if xv.ndim == 1:
                # 1-D x integrates along `axis`: place its length there
                shape = [1] * yv.ndim
                shape[axis % yv.ndim] = xv.shape[0]
                xv = xv.reshape(shape)
            x1 = jnp.moveaxis(jnp.broadcast_to(xv, yv.shape), axis, -1)
            areas = heights * (x1[..., 1:] - x1[..., :-1])
        return jnp.moveaxis(jnp.cumsum(areas, axis=-1), -1, axis)

    if x is not None:
        return apply(_cumtrap, y, _t(x))
    return apply(_cumtrap, y)


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize sub-tensors along `axis` so each slice's p-norm <= max_norm.
    Parity: paddle.renorm (reference operators/renorm_op.cc semantics)."""
    x = _t(x)

    def fn(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return apply(fn, x)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Parity: operators/cum_op (logcumsumexp) — running log-sum-exp."""
    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        if d is not None:
            vv = vv.astype(d)  # reference casts BEFORE the scan: accumulation
            # runs in the requested precision, not the input's
        a = 0 if axis is None else int(axis)
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=a)

    return apply(fn, _t(x))


def sgn(x, name=None):
    """Parity: paddle.sgn — sign for real, unit phasor for complex."""
    def fn(v):
        if jnp.iscomplexobj(v):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0.0 + 0.0j, v / jnp.where(mag == 0, 1.0, mag))
        return jnp.sign(v)

    return apply(fn, _t(x))


def frexp(x, name=None):
    return apply(lambda v: tuple(jnp.frexp(v)), _t(x))


def ldexp(x, y, name=None):
    return apply(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), _t(x), _t(y))


def copysign(x, y, name=None):
    y = _t(y) if hasattr(y, "ndim") or isinstance(y, (list, tuple)) else y
    if isinstance(y, (int, float)):
        return apply(lambda a: jnp.copysign(a, y), _t(x))
    return apply(jnp.copysign, _t(x), y)


def nextafter(x, y, name=None):
    # not differentiable (no JVP rule); zero-grad like the reference op
    return apply(lambda a, b: jnp.nextafter(jax.lax.stop_gradient(a),
                                            jax.lax.stop_gradient(b)),
                 _t(x), _t(y))


def i0(x, name=None):
    return apply(lambda v: jax.scipy.special.i0(v), _t(x))


def polygamma(x, n, name=None):
    return apply(lambda v: jax.scipy.special.polygamma(int(n), v), _t(x))


def vander(x, n=None, increasing=False, name=None):
    """Parity: paddle.vander (Vandermonde matrix)."""
    def fn(v):
        cols = v.shape[0] if n is None else int(n)
        p = jnp.arange(cols)
        if not increasing:
            p = p[::-1]
        return v[:, None] ** p[None, :]

    return apply(fn, _t(x))


def add_n(inputs, name=None):
    """sum_op.cc parity: elementwise sum of a list of same-shape tensors."""
    if isinstance(inputs, Tensor):
        return inputs
    ts = [_t(x) for x in inputs]
    out = ts[0]
    for t in ts[1:]:
        out = out + t
    return out


def tanh_(x, name=None):
    return apply_inplace(jnp.tanh, _t(x))
