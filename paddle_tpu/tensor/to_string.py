"""Tensor print options (reference python/paddle/tensor/to_string.py:34
set_printoptions). Tensor.__repr__ renders its array through these."""
import numpy as np

_OPTIONS = {"precision": 8, "threshold": 1000, "edgeitems": 3,
            "linewidth": 80, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """numpy-style print options for Tensor reprs; None leaves a field as-is."""
    for k, v in (("precision", precision), ("threshold", threshold),
                 ("edgeitems", edgeitems), ("linewidth", linewidth)):
        if v is not None:
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise TypeError(f"set_printoptions: {k} must be a "
                                f"non-negative int, got {v!r}")
            _OPTIONS[k] = v
    if sci_mode is not None:
        _OPTIONS["sci_mode"] = bool(sci_mode)


def array_repr(arr):
    """Render an array honoring set_printoptions (used by Tensor.__repr__)."""
    a = np.asarray(arr)
    if a.dtype.kind == "V":  # ml_dtypes (bf16/fp8) register as void-kind:
        a = a.astype(np.float32)  # render through f32 so options apply
    kw = {}
    if np.issubdtype(a.dtype, np.floating):
        kw["precision"] = _OPTIONS["precision"]
        if _OPTIONS["sci_mode"] is not None:
            kw["suppress_small"] = not _OPTIONS["sci_mode"]
            if _OPTIONS["sci_mode"]:
                kw["formatter"] = {"float_kind": lambda x: f"{x:.{_OPTIONS['precision']}e}"}
    return np.array2string(a, threshold=_OPTIONS["threshold"],
                           edgeitems=_OPTIONS["edgeitems"],
                           max_line_width=_OPTIONS["linewidth"], **kw)
