"""Search/sort ops (python/paddle/tensor/search.py parity: argmax, argmin, argsort, sort,
topk, index_select, nonzero, kthvalue, mode, searchsorted, bucketize, masked_select)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = apply(lambda v: jnp.argmax(v, axis=axis, keepdims=keepdim).astype(jnp.int64), _t(x).detach())
    out.stop_gradient = True
    return out


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = apply(lambda v: jnp.argmin(v, axis=axis, keepdims=keepdim).astype(jnp.int64), _t(x).detach())
    out.stop_gradient = True
    return out


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        idx = jnp.argsort(v, axis=axis, stable=True)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)

    out = apply(fn, _t(x).detach())
    out.stop_gradient = True
    return out


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out

    return apply(fn, _t(x))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    x = _t(x)
    ax = -1 if axis is None else axis

    def fn(v):
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vv, k)
        else:
            vals, idx = jax.lax.top_k(-vv, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    vals, idx = apply(fn, x)
    idx.stop_gradient = True
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        s = jnp.sort(v, axis=axis)
        i = jnp.argsort(v, axis=axis, stable=True)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    vals, idx = apply(fn, _t(x))
    idx.stop_gradient = True
    return vals, idx


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis (eager/numpy path — dynamic by nature)."""
    arr = np.asarray(_t(x)._data)

    def _mode1d(a):
        vals, counts = np.unique(a, return_counts=True)
        m = vals[np.argmax(counts)]
        # paddle returns the last index of the mode value
        idx = np.nonzero(a == m)[0][-1]
        return m, idx

    moved = np.moveaxis(arr, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    ms = np.empty(flat.shape[0], dtype=arr.dtype)
    ids = np.empty(flat.shape[0], dtype=np.int64)
    for r in range(flat.shape[0]):
        ms[r], ids[r] = _mode1d(flat[r])
    out_shape = moved.shape[:-1]
    ms = ms.reshape(out_shape)
    ids = ids.reshape(out_shape)
    if keepdim:
        ms = np.expand_dims(ms, axis)
        ids = np.expand_dims(ids, axis)
    return Tensor(jnp.asarray(ms)), Tensor(jnp.asarray(ids))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def fn(s, v):
        side = "right" if right else "left"
        out = jnp.searchsorted(s, v, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    out = apply(fn, _t(sorted_sequence).detach(), _t(values).detach())
    out.stop_gradient = True
    return out


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_fill(x, index, axis, value, name=None):
    def fn(v, i):
        i = i.astype(jnp.int32)
        idx = [jnp.arange(s) for s in v.shape]
        val = value._data if isinstance(value, Tensor) else value
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[i].set(val)
        return jnp.moveaxis(moved, 0, axis)

    return apply(fn, _t(x), _t(index).detach())


def where_index(condition):
    from .manipulation import nonzero

    return nonzero(condition)
