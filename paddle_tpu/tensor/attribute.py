"""Attribute ops (python/paddle/tensor/attribute.py parity)."""
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor


def shape(x):
    """Returns the shape as an int32 tensor (operators/shape_op.cc parity)."""
    return Tensor(jnp.asarray(np.array(x.shape, dtype=np.int32)))


def rank(x):
    return Tensor(jnp.asarray(np.array(x.ndim, dtype=np.int32)))


def is_floating_point(x):
    return dtype_mod.is_floating(x.dtype)


def is_integer(x):
    return dtype_mod.is_integer(x.dtype)


def is_complex(x):
    return dtype_mod.is_complex(x.dtype)
