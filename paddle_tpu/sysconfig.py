"""paddle.sysconfig parity: include/lib dirs of the installed package."""
import os


def get_include():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "include")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
