"""AMP autocast.

Reference parity: paddle/fluid/imperative/amp_auto_cast.cc (white/black op lists, input
casting in Tracer::TraceOp) + python/paddle/fluid/dygraph/amp/auto_cast.py:91 amp_guard.

TPU-native design: instead of per-op kernel-dtype choice, the autocast context installs a
dispatch-level input cast: ops in the white list (matmul/conv — the MXU ops) run in
bfloat16 (or float16), black-list ops (softmax/log/reductions in loss) stay float32.
Hooked via core.dispatch by wrapping the op's tensor inputs.
"""
import contextlib

import jax.numpy as jnp

from ..core import dtype as dtype_mod

# operators/amp lists parity (imperative/amp_auto_cast.cc white/black lists)
white_list = {"matmul", "conv2d", "conv1d", "conv3d", "linear", "einsum", "bmm", "mm", "mv", "addmm"}
black_list = {"exp", "log", "softmax", "log_softmax", "cross_entropy", "mean", "sum", "cosh", "sinh", "softmax_with_cross_entropy"}

_STATE = {"enabled": False, "dtype": None, "level": "O1", "custom_white": set(), "custom_black": set()}


def amp_state():
    return _STATE


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16"):
    old = dict(_STATE)
    _STATE["enabled"] = enable
    _STATE["dtype"] = dtype_mod.convert_dtype(dtype)
    _STATE["level"] = level
    _STATE["custom_white"] = set(custom_white_list or ())
    _STATE["custom_black"] = set(custom_black_list or ())
    try:
        yield
    finally:
        _STATE.update(old)


amp_guard = auto_cast


def maybe_cast_inputs(op_name, vals):
    """Called by ops that participate in autocast (linear/conv/matmul paths)."""
    if not _STATE["enabled"]:
        return vals
    name = op_name
    if name in _STATE["custom_black"] or (name in black_list and name not in _STATE["custom_white"]):
        return [v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) else v for v in vals]
    if _STATE["level"] == "O2" or name in white_list or name in _STATE["custom_white"]:
        d = _STATE["dtype"]
        return [v.astype(d) if jnp.issubdtype(v.dtype, jnp.floating) else v for v in vals]
    return vals


def amp_dtype():
    return _STATE["dtype"] if _STATE["enabled"] else None
