"""Numeric debugging: FLAGS_check_nan_inf parity
(framework/details/nan_inf_utils_detail.cc — after-kernel NaN/Inf scan and abort).
TPU-native: a dispatch-level post-op check toggled by enable_operator_stats_collection /
the check_nan_inf flag, plus jax.debug_nans passthrough."""
import contextlib

import jax
import jax.numpy as jnp

from .. import flags


class NaNInfError(FloatingPointError):
    pass


def check_numerics(tensor, op_name="op"):
    import numpy as np

    v = tensor._data if hasattr(tensor, "_data") else tensor
    if jnp.issubdtype(v.dtype, jnp.floating):
        if not bool(jnp.all(jnp.isfinite(v))):
            raise NaNInfError(f"NaN/Inf found in output of {op_name}")
    return tensor


@contextlib.contextmanager
def enable_check_nan_inf():
    flags.set_flags({"check_nan_inf": True})
    try:
        with jax.debug_nans(True):
            yield
    finally:
        flags.set_flags({"check_nan_inf": False})
