"""paddle.amp parity (python/paddle/amp/__init__.py): auto_cast + GradScaler +
white/black lists. On TPU, level 'O1' maps to bfloat16 autocast (no scaler needed,
but the scaler API is kept for parity; it is numerically a no-op pass-through when
loss scaling is disabled)."""
from .auto_cast import amp_guard, auto_cast, white_list, black_list  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401
from . import debugging  # noqa: F401


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts model params to the low-precision dtype."""
    from ..core import dtype as dtype_mod

    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        d = dtype_mod.convert_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                p._data = p._data.astype(d)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers
