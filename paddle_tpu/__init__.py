"""paddle_tpu — a TPU-native deep-learning framework with the PaddlePaddle (~v2.0)
capability surface.

Built on JAX/XLA/Pallas/pjit: eager ("dygraph") Tensors with tape autograd, a
trace-to-XLA `jit.to_static` path, the nn/tensor/optimizer/amp/io/metric API families,
a high-level Model.fit trainer, and a fleet distributed stack over jax.sharding meshes.
See SURVEY.md for the structural analysis of the reference this targets.
"""
__version__ = "0.1.0"

import os as _os

if _os.environ.get("PADDLE_TPU_FORCE_CPU"):
    # escape hatch for embedded/headless hosts where a sitecustomize pins the
    # platform before user code can call jax.config.update (e.g. the C-ABI
    # predictor host): honor the env var at first import
    import jax as _jax

    try:
        _jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

from . import flags as _flags_mod  # noqa: F401
from .core import dtype as _dtype

# dtypes (framework.proto:106 VarType.Type taxonomy)
bool = _dtype._NAME_TO_DTYPE["bool"]  # noqa: A001
uint8 = _dtype.uint8
int8 = _dtype.int8
int16 = _dtype.int16
int32 = _dtype.int32
int64 = _dtype.int64
float16 = _dtype.float16
bfloat16 = _dtype.bfloat16
float32 = _dtype.float32
float64 = _dtype.float64
complex64 = _dtype.complex64
complex128 = _dtype.complex128
set_default_dtype = _dtype.set_default_dtype
get_default_dtype = _dtype.get_default_dtype

from .core.device import (  # noqa: E402
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)
from .core.generator import seed  # noqa: E402
from .core.tape import is_grad_enabled, no_grad  # noqa: E402
from .core.tensor import ParamBase, Tensor, to_tensor  # noqa: E402
from .flags import get_flags, set_flags  # noqa: E402

from .tensor import *  # noqa: E402,F401,F403
from . import tensor  # noqa: E402

# subpackages land progressively; import what exists
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import amp  # noqa: E402
from . import autograd  # noqa: E402
from . import jit  # noqa: E402
from . import vision  # noqa: E402
from . import text  # noqa: E402
from . import static  # noqa: E402
from . import distributed  # noqa: E402
from . import linalg  # noqa: E402
from . import fft  # noqa: E402
from . import distribution  # noqa: E402
from . import onnx  # noqa: E402
from . import analysis  # noqa: E402
from . import quantization  # noqa: E402
from . import profiler as profiler  # noqa: E402
from . import monitor  # noqa: E402
# the dotted import FIRST: it forces the tracing subpackage to load and
# replaces the 'trace' attr (the tensor-star math op) with the CALLABLE
# module — paddle.trace(x) keeps the op API, paddle.trace.span() traces
from .trace import costs as _trace_costs  # noqa: E402,F401
from . import trace  # noqa: E402
from . import testing  # noqa: E402
from . import utils  # noqa: E402
from . import regularizer  # noqa: E402
from . import compat  # noqa: E402
from . import sysconfig  # noqa: E402
from . import reader  # noqa: E402
from . import dataset  # noqa: E402
from .batch import batch  # noqa: E402
from .nn import ParamAttr  # noqa: E402
from .core.generator import default_generator as _defgen  # noqa: E402


# paddle.set_printoptions parity (reference tensor/to_string.py:34):
# framework-local options consumed by Tensor.__repr__ — already re-exported
# by `from .tensor import *` above; nothing to wrap.


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.create_parameter parity: a free-standing trainable tensor."""
    from .nn.initializer import Constant, XavierNormal
    import jax.numpy as _jnp

    init = default_initializer or (Constant(0.0) if is_bias else XavierNormal())
    t = Tensor(_jnp.asarray(init(list(shape), dtype)))
    t.stop_gradient = False
    return t


def get_cudnn_version():
    """Reference device.get_cudnn_version parity: None when no cuDNN is
    present — always the case on TPU."""
    return None


def monkey_patch_variable():
    """fluid compat no-op: Tensor operator methods are installed at import
    (tensor/math_patch.py), so the fluid-era static-Variable patching the
    reference runs at startup has nothing left to do here."""
    return None


def get_cuda_rng_state():
    """Compat: returns the framework RNG seed state (no CUDA here; the
    per-device generator is the TPU analog)."""
    return [_defgen().initial_seed()]


def set_cuda_rng_state(state):
    if state:
        seed(int(state[0]))
from .autograd import grad  # noqa: E402
from .framework import io as _fio  # noqa: E402
from .hapi import callbacks  # noqa: E402
from .hapi.model import Model  # noqa: E402
from .hapi.model_summary import summary  # noqa: E402

save = _fio.save
load = _fio.load
DataParallel = distributed.DataParallel
disable_static = static.disable_static
enable_static = static.enable_static
in_dynamic_mode = static.in_dynamic_mode
from .hapi.model import flops  # noqa: E402
