"""paddle.incubate.optimizer parity: the experimental optimizer wrappers the
reference exposes here (LookAhead, ModelAverage) live in paddle_tpu.optimizer;
re-exported under the incubate path."""
from ..optimizer.extras import LookAhead, ModelAverage  # noqa: F401
