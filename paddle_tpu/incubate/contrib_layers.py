"""fluid.contrib.layers-style wrappers for the recommendation/text-matching
op family (python/paddle/fluid/contrib/layers/nn.py parity): the reference
signatures create the parameters from `param_attr`/size attrs inside the
call; these wrappers do the same via LayerHelper and delegate the math to
the functional forms in nn/functional (tests/test_rec_ops.py mirrors the
C++ kernels). Eager-friendly: each call creates fresh parameters, exactly
like the fluid helpers did under a program guard."""
import numpy as np

from ..nn import functional as F


def batch_fc(input, param_size, param_attr=None, bias_size=None,
             bias_attr=None, act=None):
    """fluid.contrib.layers.batch_fc parity
    (contrib/layers/nn.py:1382): w/bias created from the size attrs."""
    from . import LayerHelper

    helper = LayerHelper("batch_fc")
    if tuple(input.shape[0:1]) != tuple(param_size[0:1]) or \
            input.shape[2] != param_size[1]:
        raise ValueError(
            f"param_size {param_size} incompatible with input "
            f"{tuple(input.shape)}")
    w = helper.create_parameter(attr=param_attr, shape=list(param_size))
    b = None
    if bias_size is not None:
        if list(bias_size) != [param_size[0], param_size[2]]:
            raise ValueError(
                f"bias_size {bias_size} must be [slot, out] = "
                f"[{param_size[0]}, {param_size[2]}]")
        b = helper.create_parameter(attr=bias_attr, shape=list(bias_size))
    return F.batch_fc(input, w, b, act=act)


def rank_attention(input, rank_offset, rank_param_shape,
                   rank_param_attr=None, max_rank=3, max_size=0):
    """fluid.contrib.layers.rank_attention parity
    (contrib/layers/nn.py:1314), including its shape assert."""
    from . import LayerHelper

    helper = LayerHelper("rank_attention")
    if input.shape[1] * max_rank * max_rank != rank_param_shape[0]:
        raise ValueError(
            f"rank_param_shape[0] ({rank_param_shape[0]}) must equal "
            f"in_dim*max_rank^2 ({input.shape[1] * max_rank * max_rank})")
    rank_param = helper.create_parameter(attr=rank_param_attr,
                                         shape=list(rank_param_shape))
    return F.rank_attention(input, rank_offset, rank_param,
                            max_rank=max_rank, max_size=max_size)


def search_pyramid_hash(input, length, num_emb, space_len, pyramid_layer,
                        rand_len, drop_out_percent=0.0, is_training=True,
                        seed=1, step=0, param_attr=None, dtype="float32"):
    """fluid.contrib.layers.search_pyramid_hash parity
    (contrib/layers/nn.py:668): the [space_len + rand_len] hash table is
    the created parameter (the reference's white/black-list args are
    descoped with the PS filter tooling — see the functional docstring).
    Padded dialect: input [B, T] int ids + length [B]."""
    from . import LayerHelper

    helper = LayerHelper("pyramid_hash")
    weights = helper.create_parameter(attr=param_attr,
                                      shape=[space_len + rand_len],
                                      dtype=dtype)
    return F.search_pyramid_hash(
        input, length, weights, num_emb=num_emb, space_len=space_len,
        pyramid_layer=pyramid_layer, rand_len=rand_len,
        drop_out_percent=drop_out_percent, is_training=is_training,
        seed=seed, step=step)


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """fluid.contrib.layers.sequence_topk_avg_pooling parity
    (contrib/layers/nn.py:333) over the padded dialect: input
    [B, channel_num, Rmax, Cmax], row/col the per-sample lengths."""
    return F.sequence_topk_avg_pooling(input, row, col, topks=topks,
                                       channel_num=channel_num)


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """fluid.layers.filter_by_instag parity (layers/nn.py:10115)."""
    return F.filter_by_instag(ins, ins_tag, filter_tag, is_lod=is_lod,
                              out_val_if_empty=out_val_if_empty)
