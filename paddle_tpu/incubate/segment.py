"""Segment reductions (paddle.incubate.segment_* parity; reference
operators/segment_pool_op / tdm-style segment kernels). XLA-native:
jax.ops.segment_* with the segment count taken from the ids host-side
(eager API, like the reference's dynamic-output CPU kernels)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _segment(data, segment_ids, kind):
    ids_np = np.asarray(_t(segment_ids)._data).astype(np.int32)
    n = int(ids_np.max()) + 1 if ids_np.size else 0
    fns = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}

    def fn(d, ids):
        ids = ids.astype(jnp.int32)
        if kind == "mean":
            s = jax.ops.segment_sum(d, ids, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                      num_segments=n)
            shape = (n,) + (1,) * (d.ndim - 1)
            return s / jnp.maximum(cnt.reshape(shape), 1)
        out = fns[kind](d, ids, num_segments=n)
        if kind in ("max", "min"):
            # empty segments: paddle fills 0, jax fills +-inf
            cnt = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                      num_segments=n)
            shape = (n,) + (1,) * (d.ndim - 1)
            out = jnp.where(cnt.reshape(shape) > 0, out, 0)
        return out

    return apply(fn, _t(data), _t(segment_ids).detach())


def segment_sum(data, segment_ids, name=None):
    return _segment(data, segment_ids, "sum")


def segment_mean(data, segment_ids, name=None):
    return _segment(data, segment_ids, "mean")


def segment_max(data, segment_ids, name=None):
    return _segment(data, segment_ids, "max")


def segment_min(data, segment_ids, name=None):
    return _segment(data, segment_ids, "min")
