"""paddle.incubate.reader parity: the fluid reader decorators re-exported."""
from ..reader import (  # noqa: F401
    buffered, cache, chain, compose, firstn, map_readers, shuffle,
    xmap_readers,
)
