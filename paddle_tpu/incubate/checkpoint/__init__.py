from .auto_checkpoint import CheckpointSaver, TrainEpochRange, train_epoch_range  # noqa: F401
