"""Auto-checkpoint / resume.

Reference parity: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py —
TrainEpochRange:265 checkpoints program+epoch state keyed by job id;
AutoCheckpointChecker:71 restores after restart; CheckpointSaver
(checkpoint_saver.py) manages numbered checkpoints with max_num kept.

TPU-native design: orbax-style local/remote dir checkpoints of
(model state_dict, optimizer state, epoch/step counters) with atomic rename commits;
the SPMD trainer's sharded params are gathered on save, resharded on load.
"""
import json
import os
import shutil
import time

import numpy as np

from ...framework.io import load as pload
from ...framework.io import save as psave

_JOB_ID_ENV = "PADDLE_JOB_ID"
_CHECKPOINT_PATH_ENV = "PADDLE_CHECKPOINT_DIR"


class CheckpointSaver:
    """checkpoint_saver.py parity: numbered checkpoints, keep max_num."""

    def __init__(self, directory, max_num=3):
        self.directory = directory
        self.max_num = max_num
        os.makedirs(directory, exist_ok=True)

    def _ckpt_dir(self, no):
        return os.path.join(self.directory, f"__paddle_checkpoint__.{no}")

    def get_checkpoint_numbers(self):
        nums = []
        for name in os.listdir(self.directory):
            if name.startswith("__paddle_checkpoint__.") and not name.endswith(".tmp"):
                try:
                    nums.append(int(name.rsplit(".", 1)[1]))
                except ValueError:
                    pass
        return sorted(nums)

    def save_checkpoint(self, state, meta=None):
        nums = self.get_checkpoint_numbers()
        no = (nums[-1] + 1) if nums else 0
        tmp = self._ckpt_dir(no) + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        psave(state, os.path.join(tmp, "state.pdparams"))
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"no": no, "time": time.time(), **(meta or {})}, f)
        os.rename(tmp, self._ckpt_dir(no))  # atomic commit
        for old in self.get_checkpoint_numbers()[: -self.max_num]:
            shutil.rmtree(self._ckpt_dir(old), ignore_errors=True)
        return no

    def load_checkpoint(self, no=None):
        nums = self.get_checkpoint_numbers()
        if not nums:
            return None, None
        no = no if no is not None else nums[-1]
        d = self._ckpt_dir(no)
        state = pload(os.path.join(d, "state.pdparams"))
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        return state, meta


class TrainEpochRange:
    """auto_checkpoint.py:265 parity: `for epoch in TrainEpochRange(n, name):` resumes
    from the last committed epoch after a restart."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None, save_dir=None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        job_id = os.environ.get(_JOB_ID_ENV, "default_job")
        root = save_dir or os.environ.get(_CHECKPOINT_PATH_ENV, "/tmp/paddle_tpu_auto_ckpt")
        self._saver = CheckpointSaver(os.path.join(root, job_id, name))
        self._layers = []
        self._optimizers = []
        state, meta = self._saver.load_checkpoint()
        self._restored_state = state
        self._start_epoch = (meta.get("epoch", -1) + 1) if meta else 0

    def add(self, layer=None, optimizer=None):
        """Register objects whose state rides the checkpoint."""
        if layer is not None:
            self._layers.append(layer)
        if optimizer is not None:
            self._optimizers.append(optimizer)
        if self._restored_state is not None:
            for i, l in enumerate(self._layers):
                key = f"layer{i}"
                if key in self._restored_state:
                    l.set_state_dict(self._restored_state[key])
            for i, o in enumerate(self._optimizers):
                key = f"opt{i}"
                if key in self._restored_state:
                    o.set_state_dict(self._restored_state[key])
        return self

    def get(self):
        return range(self._start_epoch, self.max_epoch_num)

    def __iter__(self):
        for epoch in self.get():
            yield epoch
            self.save(epoch)

    def save(self, epoch):
        state = {}
        for i, l in enumerate(self._layers):
            state[f"layer{i}"] = l.state_dict()
        for i, o in enumerate(self._optimizers):
            state[f"opt{i}"] = o.state_dict()
        self._saver.save_checkpoint(state, meta={"epoch": epoch})


def train_epoch_range(max_epoch_num, name="train", save_dir=None):
    return TrainEpochRange(max_epoch_num, name, save_dir=save_dir)
