"""Auto-checkpoint / resume.

Reference parity: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py —
TrainEpochRange:265 checkpoints program+epoch state keyed by job id;
AutoCheckpointChecker:71 restores after restart; CheckpointSaver
(checkpoint_saver.py) manages numbered checkpoints with max_num kept.

TPU-native design: orbax-style local/remote dir checkpoints of
(model state_dict, optimizer state, epoch/step counters) with atomic rename commits;
the SPMD trainer's sharded params are gathered on save, resharded on load.
"""
import contextlib
import json
import os
import shutil
import time
import warnings

import numpy as np

from ... import flags as _flags
from ... import monitor as _monitor
from ...framework.io import CheckpointCorruptError, _fsync_dir
from ...framework.io import load as pload
from ...framework.io import save as psave
from ...testing import failpoints as _fp

_JOB_ID_ENV = "PADDLE_JOB_ID"
_CHECKPOINT_PATH_ENV = "PADDLE_CHECKPOINT_DIR"

# errors that mean THIS checkpoint's bytes are bad (evict + fall back);
# anything else — permissions, fd exhaustion, a missing encryption key —
# must propagate instead of destroying a checkpoint that may be fine
_CORRUPT_ERRORS = (CheckpointCorruptError, json.JSONDecodeError, EOFError,
                   FileNotFoundError, NotADirectoryError, UnicodeDecodeError)

# tmp dirs a save_checkpoint in THIS process is writing right now — a
# sibling CheckpointSaver constructed on another thread must not sweep them
_ACTIVE_TMPS = set()

def _goodput_bucket(name):
    """ckpt_save/ckpt_restore attribution for the SAVER's own overhead
    (FLAGS_goodput, ISSUE 20) — tmp-dir setup, meta.json, commit rename,
    rotation, and the corrupt-fallback walk-back. The inner psave/pload
    legs nest the SAME bucket via framework/io.py (harmless: one pauses
    while the other books, totals stay exclusive). Null context when the
    accountant is disarmed; the import stays manifest-lazy."""
    if not _flags.get_flag("goodput", False):
        return contextlib.nullcontext()
    from ...monitor import goodput as _goodput

    return _goodput.bucket(name)


_RECOVER = _monitor.counter(
    "checkpoint_recover_total",
    "checkpoint recovery actions by reason (corrupt = an unreadable newest "
    "checkpoint was evicted and an older one restored; tmp_swept = a stale "
    ".tmp dir from a crashed run was reclaimed)",
    labelnames=("reason",))


class CheckpointSaver:
    """checkpoint_saver.py parity: numbered checkpoints, keep max_num.

    Robustness (docs/ROBUSTNESS.md): construction sweeps orphaned
    ``__paddle_checkpoint__.*.tmp`` dirs left by crashed runs, and
    ``load_checkpoint()`` (no explicit number) walks backward to the newest
    *valid* checkpoint, evicting corrupt ones instead of crashing on them —
    a process killed mid-save never bricks the resume path."""

    def __init__(self, directory, max_num=3):
        self.directory = directory
        self.max_num = max_num
        os.makedirs(directory, exist_ok=True)
        self.sweep_tmp()

    def _ckpt_dir(self, no):
        return os.path.join(self.directory, f"__paddle_checkpoint__.{no}")

    # a marker-less tmp dir younger than this may be a concurrent saver
    # between its makedirs and its owner.pid write — don't sweep it yet
    _TMP_GRACE_S = 60.0

    @staticmethod
    def _tmp_is_orphan(tmp_dir):
        """True when a tmp dir is a reclaimable crash leftover. A dir whose
        owner.pid marker names a live OTHER process is a concurrent saver
        mid-commit in a shared directory; our own pid is live only while a
        save_checkpoint is actually inside its commit window (_ACTIVE_TMPS
        — another thread of this process), otherwise it is an aborted
        attempt. A marker-less dir gets a short grace period to cover the
        makedirs→marker-write window."""
        if os.path.abspath(tmp_dir) in _ACTIVE_TMPS:
            return False   # a saver thread in THIS process is writing it
        try:
            with open(os.path.join(tmp_dir, "owner.pid")) as f:
                pid = int(f.read().strip())
        except (OSError, ValueError):
            try:
                age = time.time() - os.stat(tmp_dir).st_mtime
            except OSError:
                return False   # vanished under us — nothing to reclaim
            return age > CheckpointSaver._TMP_GRACE_S
        if pid == os.getpid():
            return True    # ours but not active — an aborted attempt
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return True
        except OSError:
            pass           # e.g. EPERM: it exists but isn't ours
        return False

    def sweep_tmp(self):
        """Reclaim orphaned .tmp checkpoint dirs (crash-mid-save leftovers);
        returns how many were removed. Tmp dirs owned by a live concurrent
        saver (owner.pid marker, or young enough to still be writing one)
        are left alone."""
        removed = 0
        for name in os.listdir(self.directory):
            if name.startswith("__paddle_checkpoint__.") \
                    and name.endswith(".tmp"):
                path = os.path.join(self.directory, name)
                if not self._tmp_is_orphan(path):
                    continue
                shutil.rmtree(path, ignore_errors=True)
                removed += 1
        if removed and _monitor.is_enabled():
            _RECOVER.labels(reason="tmp_swept").inc(removed)
        return removed

    def get_checkpoint_numbers(self):
        nums = []
        for name in os.listdir(self.directory):
            if name.startswith("__paddle_checkpoint__.") and not name.endswith(".tmp"):
                try:
                    nums.append(int(name.rsplit(".", 1)[1]))
                except ValueError:
                    pass
        return sorted(nums)

    def save_checkpoint(self, state, meta=None):
        with _goodput_bucket("ckpt_save"):
            nums = self.get_checkpoint_numbers()
            no = (nums[-1] + 1) if nums else 0
            tmp = self._ckpt_dir(no) + ".tmp"
            _ACTIVE_TMPS.add(os.path.abspath(tmp))
            try:
                os.makedirs(tmp, exist_ok=True)
                with open(os.path.join(tmp, "owner.pid"), "w") as f:
                    f.write(str(os.getpid()))  # sweep_tmp skips live owners
                psave(state, os.path.join(tmp, "state.pdparams"))
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump({"no": no, "time": time.time(),
                               **(meta or {})}, f)
                _fp.failpoint("ckpt/commit")
                os.remove(os.path.join(tmp, "owner.pid"))
                os.rename(tmp, self._ckpt_dir(no))  # atomic commit
            finally:
                _ACTIVE_TMPS.discard(os.path.abspath(tmp))
            # make the commit durable BEFORE rotating older checkpoints
            # away: a crash here must find either the new dir or the old
            # ones on disk
            _fsync_dir(self.directory)
            for old in self.get_checkpoint_numbers()[: -self.max_num]:
                shutil.rmtree(self._ckpt_dir(old), ignore_errors=True)
            return no

    def _load_one(self, no):
        d = self._ckpt_dir(no)
        state = pload(os.path.join(d, "state.pdparams"))
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        return state, meta

    def load_checkpoint(self, no=None):
        """Newest valid checkpoint (or the explicit `no`, which raises on
        corruption instead of falling back). An unreadable newest
        checkpoint — truncated state file, missing meta, failed sha256
        footer — is EVICTED and the walk continues to the previous one,
        counting checkpoint_recover_total{reason=corrupt}."""
        with _goodput_bucket("ckpt_restore"):
            nums = self.get_checkpoint_numbers()
            if not nums:
                return None, None
            if no is not None:
                return self._load_one(no)
            for cand in reversed(nums):
                try:
                    return self._load_one(cand)
                except _CORRUPT_ERRORS as e:
                    d = self._ckpt_dir(cand)
                    warnings.warn(
                        f"checkpoint {d} is unreadable ({type(e).__name__}: "
                        f"{e}); evicting it and falling back to the "
                        "previous checkpoint")
                    shutil.rmtree(d, ignore_errors=True)
                    if _monitor.is_enabled():
                        _RECOVER.labels(reason="corrupt").inc()
            return None, None


class TrainEpochRange:
    """auto_checkpoint.py:265 parity: `for epoch in TrainEpochRange(n, name):` resumes
    from the last committed epoch after a restart."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None, save_dir=None):
        self.max_epoch_num = max_epoch_num
        self.name = name
        job_id = os.environ.get(_JOB_ID_ENV, "default_job")
        root = save_dir or os.environ.get(_CHECKPOINT_PATH_ENV, "/tmp/paddle_tpu_auto_ckpt")
        self._saver = CheckpointSaver(os.path.join(root, job_id, name))
        self._layers = []
        self._optimizers = []
        state, meta = self._saver.load_checkpoint()
        self._restored_state = state
        self._start_epoch = (meta.get("epoch", -1) + 1) if meta else 0

    def add(self, layer=None, optimizer=None):
        """Register objects whose state rides the checkpoint."""
        if layer is not None:
            self._layers.append(layer)
        if optimizer is not None:
            self._optimizers.append(optimizer)
        if self._restored_state is not None:
            for i, l in enumerate(self._layers):
                key = f"layer{i}"
                if key in self._restored_state:
                    l.set_state_dict(self._restored_state[key])
            for i, o in enumerate(self._optimizers):
                key = f"opt{i}"
                if key in self._restored_state:
                    o.set_state_dict(self._restored_state[key])
        return self

    def get(self):
        return range(self._start_epoch, self.max_epoch_num)

    def __iter__(self):
        for epoch in self.get():
            yield epoch
            self.save(epoch)

    def save(self, epoch):
        state = {}
        for i, l in enumerate(self._layers):
            state[f"layer{i}"] = l.state_dict()
        for i, o in enumerate(self._optimizers):
            state[f"opt{i}"] = o.state_dict()
        self._saver.save_checkpoint(state, meta={"epoch": epoch})


def train_epoch_range(max_epoch_num, name="train", save_dir=None):
    return TrainEpochRange(max_epoch_num, name, save_dir=save_dir)
