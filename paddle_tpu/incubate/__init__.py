"""paddle.incubate parity: auto-checkpoint, (later) sparse utils."""
from . import checkpoint  # noqa: F401
