"""paddle.incubate parity: auto-checkpoint, segment reductions; plus LoRA
fine-tuning (beyond reference)."""
from . import checkpoint  # noqa: F401
from .segment import segment_max, segment_mean, segment_min, segment_sum  # noqa: F401
from . import optimizer  # noqa: F401
from . import reader  # noqa: F401
from . import lora  # noqa: F401
from . import contrib_layers  # noqa: F401  (LayerHelper is resolved at
# call time inside its functions, so this import order is safe)


class LayerHelper:
    """fluid LayerHelper compat: create_parameter/create_variable helpers for
    code ported from fluid layers. Thin — parameters come from
    paddle.create_parameter."""

    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs

    def create_parameter(self, attr=None, shape=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        import paddle_tpu as paddle

        return paddle.create_parameter(shape, dtype, attr=attr,
                                       is_bias=is_bias,
                                       default_initializer=default_initializer)
