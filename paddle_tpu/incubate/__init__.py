"""paddle.incubate parity: auto-checkpoint, segment reductions."""
from . import checkpoint  # noqa: F401
from .segment import segment_max, segment_mean, segment_min, segment_sum  # noqa: F401
