"""LoRA (Low-Rank Adaptation) fine-tuning — a beyond-reference addition.

No equivalent in the reference tree (thisjiang/Paddle ~v2.0 predates LoRA);
this follows the LoRA recipe (Hu et al. 2021): freeze the pretrained weight
W and learn a rank-r update, y = x W + b + (alpha/r) * (x A) B, with A
gaussian-init and B zero-init so training starts from the base model
exactly. TPU notes: the low-rank path is two thin matmuls the MXU handles
well, XLA fuses the add, and because only A/B are trainable the optimizer
state (and ZeRO shards) shrink to O(r * (in+out)) per layer — SpmdTrainer
already routes non-trainable params through its frozen set
(distributed/spmd.py:146-147), so LoRA composes with dp/ZeRO/tp meshes
unchanged.

Usage::

    replaced = apply_lora(model, r=8, alpha=16,
                          target_modules=["q_proj", "v_proj"])
    opt = paddle.optimizer.AdamW(parameters=lora_parameters(model))
    ... train ...
    sd = lora_state_dict(model)      # adapter-only checkpoint
    merge_lora(model)                # fold A@B into W for serving
"""
import numpy as np

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.common import Linear
from ..nn.layer.layers import Layer

__all__ = ["LoRALinear", "apply_lora", "merge_lora", "lora_parameters",
           "lora_state_dict", "mark_only_lora_trainable", "export_lora"]


def _freeze(p):
    p.trainable = False
    p.stop_gradient = True


def _unfreeze(p):
    p.trainable = True
    p.stop_gradient = False


def _wrappable_types():
    """Linear-like layers LoRA can wrap: plain nn.Linear plus the tensor-
    parallel variants (full [in, out] weights with spmd_spec annotations;
    the tiny A/B adapters stay replicated, the frozen base keeps its
    sharding — GSPMD reconciles the replicated low-rank add)."""
    from ..distributed.split import ColumnParallelLinear, RowParallelLinear

    return (Linear, ColumnParallelLinear, RowParallelLinear)


class LoRALinear(Layer):
    """Wraps an existing ``nn.Linear`` (or Column/RowParallelLinear); the
    base weight/bias are frozen and only ``lora_A``/``lora_B`` train.
    ``merge()`` folds the adapter back into the base layer for
    zero-overhead serving."""

    def __init__(self, base, r=8, alpha=None, dropout=0.0):
        super().__init__()
        if not isinstance(base, _wrappable_types()):
            raise TypeError(f"LoRALinear wraps nn.Linear or the tensor-"
                            f"parallel Linears, got {type(base)}")
        if r <= 0:
            raise ValueError(f"rank must be positive, got {r}")
        self.base = base
        _freeze(base.weight)
        if base.bias is not None:
            _freeze(base.bias)
        self.r = r
        self.scaling = (alpha if alpha is not None else r) / r
        self.dropout_p = dropout
        self.lora_A = self.create_parameter(
            shape=[base.in_features, r],
            default_initializer=I.Normal(0.0, 0.02))
        self.lora_B = self.create_parameter(
            shape=[r, base.out_features],
            default_initializer=I.Constant(0.0))

    def forward(self, x):
        y = self.base(x)
        h = x
        if self.dropout_p:
            h = F.dropout(h, p=self.dropout_p, training=self.training)
        delta = F.linear(F.linear(h, self.lora_A), self.lora_B)
        return y + delta * self.scaling

    def merge(self):
        """Fold scaling * A @ B into the base weight and return the base
        layer (a plain or tensor-parallel Linear, unfrozen — set_value
        keeps the parameter object, so spmd_spec survives), dropping the
        adapter."""
        w = np.asarray(self.base.weight.numpy())
        a = np.asarray(self.lora_A.numpy())
        b = np.asarray(self.lora_B.numpy())
        self.base.weight.set_value((w + self.scaling * (a @ b)).astype(w.dtype))
        _unfreeze(self.base.weight)
        if self.base.bias is not None:
            _unfreeze(self.base.bias)
        return self.base

    def extra_repr(self):
        return (f"in={self.base.in_features}, out={self.base.out_features}, "
                f"r={self.r}, scaling={self.scaling}")


def _iter_linear_sites(layer, target_modules):
    """Yield (parent, attr_key, qualified_name) for every nn.Linear to wrap.
    target_modules: substrings matched against the qualified sublayer name
    (HF-style, e.g. ["q_proj", "v_proj"]); None matches every Linear."""
    sites = []

    wrap_types = _wrappable_types()

    def walk(parent, prefix):
        for key, sub in list(parent._sub_layers.items()):
            if sub is None:
                continue
            qual = f"{prefix}.{key}" if prefix else key
            if isinstance(sub, LoRALinear):
                continue  # never double-wrap (also skips its .base)
            if isinstance(sub, wrap_types):
                if target_modules is None or any(t in qual
                                                 for t in target_modules):
                    sites.append((parent, key, qual))
            else:
                walk(sub, qual)

    walk(layer, "")
    return sites


def apply_lora(layer, r=8, alpha=None, dropout=0.0, target_modules=None,
               freeze_rest=True):
    """Replace matching ``nn.Linear`` sublayers with ``LoRALinear`` in place.
    Returns the list of qualified names replaced. With ``freeze_rest`` (the
    default) every other parameter is frozen, so ``layer.parameters()``
    handed to an optimizer trains adapters only; ``merge_lora`` restores the
    pre-LoRA trainable set. A Linear registered under several parents
    (module aliasing / weight tying) gets ONE shared adapter."""
    sites = _iter_linear_sites(layer, target_modules)
    if not sites:
        raise ValueError(
            f"no nn.Linear sublayer matched target_modules={target_modules}")
    # first-seen wins: a second apply_lora (disjoint target_modules) must not
    # overwrite the original snapshot with the post-freeze_rest state, or
    # merge_lora would permanently freeze unrelated params. Params living
    # under a PREVIOUS apply_lora's wrappers are excluded by wrapper
    # MEMBERSHIP (not name patterns — a user module legitimately named
    # 'base' must stay in the snapshot): their '.base.'/'lora_*' names are
    # dead keys once merge restores the pre-wrap name shape.
    wrapped_prefixes = [qual for qual, sub in layer.named_sublayers()
                        if isinstance(sub, LoRALinear)]

    def _under_wrapper(name):
        return any(name.startswith(p + ".") for p in wrapped_prefixes)

    prev_trainable = {n: getattr(p, "trainable", True)
                      for n, p in layer.named_parameters()
                      if not _under_wrapper(n)}
    prev_trainable.update(layer.__dict__.get("_lora_prev_trainable", {}))
    wrappers = {}  # id(base Linear) -> its single shared LoRALinear
    for parent, key, _ in sites:
        base = parent._sub_layers[key]
        if id(base) not in wrappers:
            wrappers[id(base)] = LoRALinear(base, r=r, alpha=alpha,
                                            dropout=dropout)
        parent._sub_layers[key] = wrappers[id(base)]
    if freeze_rest:
        mark_only_lora_trainable(layer)
    layer.__dict__["_lora_prev_trainable"] = prev_trainable
    return [qual for _, _, qual in sites]


def mark_only_lora_trainable(layer):
    """Freeze every parameter except lora_A/lora_B."""
    for name, p in layer.named_parameters():
        if "lora_A" in name or "lora_B" in name:
            _unfreeze(p)
        else:
            _freeze(p)


def merge_lora(layer):
    """Recursively fold every LoRALinear back into its base layer (plain or
    tensor-parallel Linear, in place) and restore the pre-apply_lora
    trainable set. Returns the number of
    distinct adapters merged (a shared adapter merges once even if it is
    registered under several parents)."""
    merged_bases = {}  # id(wrapper) -> merged base Linear

    def walk(parent):
        for key, sub in list(parent._sub_layers.items()):
            if sub is None:
                continue
            if isinstance(sub, LoRALinear):
                if id(sub) not in merged_bases:
                    merged_bases[id(sub)] = sub.merge()
                parent._sub_layers[key] = merged_bases[id(sub)]
            else:
                walk(sub)

    walk(layer)
    prev = layer.__dict__.pop("_lora_prev_trainable", None)
    if prev is not None:
        for n, p in layer.named_parameters():
            if n in prev:
                (_unfreeze if prev[n] else _freeze)(p)
    return len(merged_bases)


def lora_parameters(layer):
    """The trainable adapter parameters (for the optimizer)."""
    return [p for n, p in layer.named_parameters()
            if "lora_A" in n or "lora_B" in n]


def lora_state_dict(layer):
    """Adapter-only checkpoint: {qualified_name: numpy array} for A/B."""
    return {n: np.asarray(p.numpy()) for n, p in layer.named_parameters()
            if "lora_A" in n or "lora_B" in n}


def export_lora(layer):
    """One adapter in serving-export form: ``{"rank": r, "scaling": s,
    "factors": {qualified_name: {"A": [in, r], "B": [r, out]}}}`` with
    plain numpy factors. This is the unit ``ServingEngine.load_adapter``
    accepts — the decode model's ``lora_pack`` maps the qualified names
    onto its stacked per-layer sites. Rank and scaling must be uniform
    across sites: the batched multi-LoRA decode stacks every adapter into
    ONE ``[S, L, in, r]`` tensor, so there is no per-site rank axis."""
    factors, ranks, scalings = {}, set(), set()
    for qual, sub in layer.named_sublayers():
        if isinstance(sub, LoRALinear):
            factors[qual] = {"A": np.asarray(sub.lora_A.numpy()),
                             "B": np.asarray(sub.lora_B.numpy())}
            ranks.add(int(sub.r))
            scalings.add(float(sub.scaling))
    if not factors:
        raise ValueError(
            "export_lora: no LoRALinear sublayers found — apply_lora first "
            "(merged adapters cannot be exported; keep them un-merged for "
            "multi-LoRA serving)")
    if len(ranks) != 1 or len(scalings) != 1:
        raise ValueError(
            f"export_lora: multi-LoRA serving needs ONE uniform rank and "
            f"scaling per adapter, got ranks={sorted(ranks)}, "
            f"scalings={sorted(scalings)}")
    return {"rank": ranks.pop(), "scaling": scalings.pop(),
            "factors": factors}
