"""Minimal ONNX protobuf wire format — emitter AND parser, no `onnx` dep.

The reference delegates ONNX emission to the external `paddle2onnx` package
(/root/reference/python/paddle/onnx/export.py:21); this build has no `onnx`
package in-image, so the length-delimited protobuf wire format is hand-rolled
here from the public onnx.proto schema. Only the message subset the exporter
emits is modeled (ModelProto / GraphProto / NodeProto / TensorProto /
ValueInfoProto / AttributeProto). The parser reads back exactly this subset —
export.py round-trips every written file through it and re-executes the graph
in numpy as a structural + numerical self-check.

Wire format recap: each field is a (tag, payload) pair; tag = field_number<<3
| wire_type; wire_type 0 = varint, 1 = 64-bit, 2 = length-delimited, 5 =
32-bit. Packed repeated scalars are a length-delimited blob of varints/fixed.
"""
import struct

# --- TensorProto.DataType enum (public onnx.proto values) -------------------
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = range(1, 10)
FLOAT16, DOUBLE, UINT32, UINT64 = 10, 11, 12, 13
BFLOAT16 = 16

NP_TO_ONNX = {
    "float32": FLOAT, "float64": DOUBLE, "float16": FLOAT16,
    "bfloat16": BFLOAT16, "int32": INT32, "int64": INT64, "int8": INT8,
    "uint8": UINT8, "bool": BOOL, "uint32": UINT32, "uint64": UINT64,
    "int16": INT16, "uint16": UINT16,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# --- AttributeProto.AttributeType enum --------------------------------------
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_GRAPH = 1, 2, 3, 4, 5
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


# ---------------------------------------------------------------------------
# wire-level encoding
# ---------------------------------------------------------------------------

def _varint(n):
    if n < 0:  # protobuf int64: negatives are 10-byte two's complement
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def f_varint(field, value):
    return _tag(field, 0) + _varint(int(value))


def f_bytes(field, payload):
    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    return _tag(field, 2) + _varint(len(payload)) + payload


def f_float(field, value):
    return _tag(field, 5) + struct.pack("<f", float(value))


# ---------------------------------------------------------------------------
# message builders (return serialized bytes)
# ---------------------------------------------------------------------------

def tensor_proto(name, array):
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9 (little-endian)."""
    import numpy as np

    arr = np.asarray(array)
    # ascontiguousarray promotes 0-d to 1-d — restore the true shape
    arr = np.ascontiguousarray(arr).reshape(arr.shape)
    dt = NP_TO_ONNX[str(arr.dtype)]
    out = b""
    for d in arr.shape:
        out += f_varint(1, d)
    out += f_varint(2, dt)
    out += f_bytes(8, name)
    out += f_bytes(9, arr.tobytes())
    return out


def attribute(name, value):
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, g=6, floats=7, ints=8,
    strings=9, type=20."""
    out = f_bytes(1, name)
    if isinstance(value, bool):
        out += f_varint(3, int(value)) + f_varint(20, A_INT)
    elif isinstance(value, int):
        out += f_varint(3, value) + f_varint(20, A_INT)
    elif isinstance(value, float):
        out += f_float(2, value) + f_varint(20, A_FLOAT)
    elif isinstance(value, (bytes, str)):
        out += f_bytes(4, value) + f_varint(20, A_STRING)
    elif isinstance(value, (list, tuple)):
        import numbers
        import numpy as _np
        if not value:
            raise TypeError(
                f"attribute {name!r}: empty list has no inferable ONNX type; "
                "pass an explicit scalar or drop the attribute")
        is_float = lambda v: isinstance(v, (float, _np.floating))
        is_int = lambda v: isinstance(v, numbers.Integral)
        if all(is_float(v) for v in value):
            for v in value:
                out += f_float(7, float(v))
            out += f_varint(20, A_FLOATS)
        elif all(is_int(v) for v in value):
            for v in value:
                out += f_varint(8, int(v))
            out += f_varint(20, A_INTS)
        else:
            raise TypeError(
                f"attribute {name!r}: mixed/unsupported element types "
                f"{[type(v).__name__ for v in value]}")
    else:
        raise TypeError(f"unsupported attribute value {value!r}")
    return out


def node_proto(op_type, inputs, outputs, name="", attrs=None):
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b""
    for i in inputs:
        out += f_bytes(1, i)
    for o in outputs:
        out += f_bytes(2, o)
    if name:
        out += f_bytes(3, name)
    out += f_bytes(4, op_type)
    for k, v in (attrs or {}).items():
        out += f_bytes(5, attribute(k, v))
    return out


def value_info(name, elem_type, shape):
    """ValueInfoProto: name=1, type=2; TypeProto.tensor_type=1;
    Tensor: elem_type=1, shape=2; TensorShapeProto.dim=1; dim_value=1,
    dim_param=2 (a str entry in `shape` becomes a symbolic dimension —
    the dynamic-batch export path emits 'N' for the batch axis)."""
    shape_body = b""
    for d in shape:
        if isinstance(d, str):
            shape_body += f_bytes(1, f_bytes(2, d))
        else:
            shape_body += f_bytes(1, f_varint(1, int(d)))
    tensor_body = f_varint(1, elem_type) + f_bytes(2, shape_body)
    type_body = f_bytes(1, tensor_body)
    return f_bytes(1, name) + f_bytes(2, type_body)


def graph_proto(name, nodes, initializers, inputs, outputs):
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b""
    for n in nodes:
        out += f_bytes(1, n)
    out += f_bytes(2, name)
    for t in initializers:
        out += f_bytes(5, t)
    for vi in inputs:
        out += f_bytes(11, vi)
    for vi in outputs:
        out += f_bytes(12, vi)
    return out


def model_proto(graph, opset=13, producer="paddle_tpu"):
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8;
    OperatorSetIdProto: domain=1, version=2."""
    opset_body = f_bytes(1, "") + f_varint(2, opset)
    return (f_varint(1, 8)            # IR version 8 (supports opset 13)
            + f_bytes(2, producer)
            + f_bytes(7, graph)
            + f_bytes(8, opset_body))


# ---------------------------------------------------------------------------
# parser (reads back the subset above)
# ---------------------------------------------------------------------------

def _read_varint(buf, pos):
    shift, val = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a serialized message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            val = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _signed64(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_tensor(buf):
    import numpy as np

    dims, dtype, name, raw = [], None, "", b""
    for field, _, val in _fields(buf):
        if field == 1:
            dims.append(_signed64(val))
        elif field == 2:
            dtype = val
        elif field == 8:
            name = val.decode()
        elif field == 9:
            raw = val
    arr = np.frombuffer(raw, dtype=ONNX_TO_NP[dtype]).reshape(dims)
    return name, arr


def parse_attribute(buf):
    name, atype, fv, iv, sv, floats, ints = "", None, None, None, None, [], []
    for field, _, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            fv = val
        elif field == 3:
            iv = _signed64(val)
        elif field == 4:
            sv = val
        elif field == 7:
            floats.append(val)
        elif field == 8:
            ints.append(_signed64(val))
        elif field == 20:
            atype = val
    if atype == A_FLOAT:
        return name, fv
    if atype == A_INT:
        return name, iv
    if atype == A_STRING:
        return name, sv
    if atype == A_FLOATS:
        return name, floats
    if atype == A_INTS:
        return name, ints
    raise ValueError(f"unsupported attribute type {atype} for {name!r}")


def parse_node(buf):
    inputs, outputs, name, op_type, attrs = [], [], "", "", {}
    for field, _, val in _fields(buf):
        if field == 1:
            inputs.append(val.decode())
        elif field == 2:
            outputs.append(val.decode())
        elif field == 3:
            name = val.decode()
        elif field == 4:
            op_type = val.decode()
        elif field == 5:
            k, v = parse_attribute(val)
            attrs[k] = v
    return {"op_type": op_type, "inputs": inputs, "outputs": outputs,
            "name": name, "attrs": attrs}


def parse_value_info(buf):
    name, elem_type, shape = "", None, []
    for field, _, val in _fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:
            for f2, _, v2 in _fields(val):      # TypeProto
                if f2 == 1:                      # tensor_type
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            elem_type = v3
                        elif f3 == 2:            # shape
                            for f4, _, v4 in _fields(v3):
                                if f4 == 1:      # dim
                                    for f5, _, v5 in _fields(v4):
                                        if f5 == 1:
                                            shape.append(_signed64(v5))
    return {"name": name, "elem_type": elem_type, "shape": shape}


def parse_graph(buf):
    nodes, inits, inputs, outputs, name = [], {}, [], [], ""
    for field, _, val in _fields(buf):
        if field == 1:
            nodes.append(parse_node(val))
        elif field == 2:
            name = val.decode()
        elif field == 5:
            n, arr = parse_tensor(val)
            inits[n] = arr
        elif field == 11:
            inputs.append(parse_value_info(val))
        elif field == 12:
            outputs.append(parse_value_info(val))
    return {"name": name, "nodes": nodes, "initializers": inits,
            "inputs": inputs, "outputs": outputs}


def parse_model(buf):
    graph, ir_version, opset, producer = None, None, None, ""
    for field, _, val in _fields(buf):
        if field == 1:
            ir_version = val
        elif field == 2:
            producer = val.decode()
        elif field == 7:
            graph = parse_graph(val)
        elif field == 8:
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    opset = v2
    return {"ir_version": ir_version, "producer": producer,
            "opset": opset, "graph": graph}
