"""paddle.onnx.export parity (reference python/paddle/onnx/export.py:21).

The reference delegates to the external `paddle2onnx` converter over a
`jit.save`d TranslatedLayer. The TPU-native export pipeline is StableHLO
(jit.save → jax.export artifact, see inference/predictor.py); ONNX is an
optional interop tail that would need a real op-by-op converter (paddle2onnx's
job). We always save the framework-native portable artifact at `path`; since
no converter ships in this build, a `.onnx` protobuf is NEVER written — an
executable-looking-but-empty .onnx would be worse than an honest error.
"""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Save `layer` at `path` in the framework-native portable format, then
    raise: ONNX protobuf emission needs an op-by-op converter this build does
    not include (the reference itself defers to the external `paddle2onnx`).
    The saved artifact is loadable via paddle_tpu.jit.load / the inference
    Predictor, and its `.pdmodel.stablehlo` is consumable by any XLA runtime.
    """
    from .. import jit as pjit

    pjit.save(layer, path, input_spec=input_spec)
    raise RuntimeError(
        "paddle_tpu.onnx.export: op-by-op ONNX conversion is not bundled "
        "(the reference delegates this to the external 'paddle2onnx' "
        "package). The model WAS saved in the framework-native StableHLO/"
        f"jax.export format at '{path}' — load it with paddle_tpu.jit.load "
        "or the inference Predictor, or feed the .pdmodel.stablehlo to any "
        "XLA-compatible runtime. No .onnx file was written."
    )
