"""paddle.onnx.export parity (reference python/paddle/onnx/export.py:21).

The reference delegates to the external `paddle2onnx` converter over a
`jit.save`d TranslatedLayer. The TPU-native export pipeline is StableHLO
(jit.save → jaxpr/StableHLO, see inference/predictor.py); ONNX is an optional
interop tail that needs the `onnx` package. When it is unavailable (this image
does not bundle it), we still honor the API: trace the layer, save the portable
StableHLO/program artifact next to the requested path, and raise a clear error
only if the caller insists on a .onnx protobuf.
"""
import os


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` for interop. Writes `path`.onnx when the `onnx` package is
    importable; always writes the framework-native saved program at `path`."""
    from .. import jit as pjit

    pjit.save(layer, path, input_spec=input_spec)
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            "paddle_tpu.onnx.export: the 'onnx' package is not installed in this "
            "environment. The model was saved in the framework-native StableHLO/"
            f"program format at '{path}' (loadable via paddle_tpu.jit.load or the "
            "inference Predictor). Install 'onnx' to emit a .onnx protobuf."
        ) from e
    # onnx available: emit a minimal model proto carrying the saved program as
    # an external reference (full op-by-op conversion is out of scope here).
    model = onnx.ModelProto()
    model.ir_version = onnx.IR_VERSION
    model.opset_import.add().version = opset_version
    model.producer_name = "paddle_tpu"
    model.doc_string = f"StableHLO program saved at {os.path.abspath(path)}"
    with open(path + ".onnx", "wb") as f:
        f.write(model.SerializeToString())
    return path + ".onnx"
