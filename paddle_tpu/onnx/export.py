"""paddle.onnx.export parity (reference python/paddle/onnx/export.py:21).

The reference delegates to the external `paddle2onnx` converter over a
`jit.save`d program. No `onnx` package ships in this image, so this build
carries its own pipeline: trace the layer ONCE to a jaxpr (the same
functional trace jit/export use), lower each primitive to standard ONNX
opset-13 ops (converter.py), and emit the protobuf wire format by hand
(proto.py). Every written file is then parsed back and re-executed in pure
numpy (runtime.py) against the layer's own output — a structural AND
numerical self-check; export fails loudly rather than writing an .onnx
that doesn't reproduce the model.

The framework-native portable artifact (StableHLO via jit.save) is written
alongside, matching the r3 behavior; `.onnx` is the interop surface.
"""
import numpy as np

__all__ = ["export"]


def _example_arrays(spec_list):
    """Concrete example inputs from InputSpec/Tensor specs: deterministic
    values (validation compares numerics, so zeros would under-test)."""
    rng = np.random.RandomState(0)
    out = []
    for s in spec_list:
        shape = tuple(2 if d is None or int(d) < 0 else int(d)
                      for d in s.shape)
        dt = np.dtype(getattr(s, "dtype", "float32") or "float32")
        if np.issubdtype(dt, np.floating):
            out.append(rng.uniform(-1, 1, shape).astype(dt))
        else:
            out.append(np.zeros(shape, dt))  # safe for index-typed inputs
    return out


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export `layer` to `path + '.onnx'` (reference signature & suffix
    convention). input_spec: list of InputSpec/Tensors describing forward
    inputs; required (the reference pulls it off the @to_static forward
    when absent — same here). opset_version: only 13 is emitted; other
    requested versions still emit 13 (the reference similarly clamps to
    what paddle2onnx supports).

    configs: `output_spec` accepted for signature parity (ignored — all
    forward outputs are exported); `atol`/`rtol` override the validation
    tolerances (defaults 1e-5); `validate=False` skips the numpy
    re-execution (e.g. huge models); `dynamic_batch` (default True)
    controls whether InputSpec dims of None/-1 on axis 0 become a symbolic
    'N' batch dimension in the emitted graph — proven sound by a second
    trace at batch+1 (converter._batch_polymorphic_rewrite) and validated
    by re-executing at BOTH batch sizes; models whose graphs genuinely
    depend on the batch size raise UnsupportedOpError under it.

    Raises converter.UnsupportedOpError if the traced graph contains a
    primitive with no ONNX lowering — no .onnx is written in that case
    (an executable-looking-but-wrong .onnx would be worse than an error);
    the framework-native artifact IS still saved.
    """
    from .. import jit as pjit
    from ..jit import StaticFunction
    from ..static import io
    from . import converter, runtime

    if opset_version != 13:
        import warnings
        warnings.warn(
            f"paddle_tpu.onnx.export: requested opset {opset_version} but "
            "only opset 13 is emitted; the produced file declares 13 and an "
            "older runtime may reject it", stacklevel=2)

    # native portable artifact alongside, as before (jit.save handles specs)
    pjit.save(layer, path, input_spec=input_spec)

    spec = input_spec
    if spec is None and isinstance(getattr(layer, "forward", None),
                                   StaticFunction):
        spec = layer.forward._input_spec
    if spec is None:
        raise ValueError(
            "paddle_tpu.onnx.export: input_spec is required (or export a "
            "@to_static layer with a recorded spec)")
    spec_list = pjit._to_spec_list(spec)
    args = _example_arrays(spec_list)

    params_named = [(n, np.asarray(t._data))
                    for n, t in layer.state_dict().items()]
    names = [n for n, _ in params_named]
    pure_d = io.layer_pure_fn(layer, force_eval=True)  # inference graph

    def pure(plist, *xs):
        import jax

        out = pure_d(dict(zip(names, plist)), *xs)
        # fully flatten nested outputs (e.g. LSTM's (out, (h, c))) — the
        # graph outputs are the flat leaves, in tree order
        return jax.tree_util.tree_leaves(out)

    input_names = [getattr(s, "name", None) or f"input_{i}"
                   for i, s in enumerate(spec_list)]
    dyn_axes = None
    if configs.get("dynamic_batch", True):
        dyn_axes = [bool(getattr(s, "shape", None))
                    and len(s.shape) > 0
                    and (s.shape[0] is None or int(s.shape[0]) < 0)
                    for s in spec_list]
        if not any(dyn_axes):
            dyn_axes = None
    model_bytes = converter.convert(pure, params_named, args,
                                    input_names=input_names,
                                    dynamic_batch_axes=dyn_axes)

    if configs.get("validate", True):
        atol = configs.get("atol", 1e-5)
        rtol = configs.get("rtol", 1e-5)

        def check(arg_set):
            expect = [np.asarray(v) for v in
                      pure([v for _, v in params_named], *arg_set)]
            got = runtime.run(model_bytes,
                              dict(zip(input_names, arg_set)))
            if len(got) != len(expect):
                raise RuntimeError(
                    f"onnx.export self-check: output arity {len(got)} != "
                    f"{len(expect)}")
            for i, (a, b) in enumerate(zip(got, expect)):
                if tuple(a.shape) != tuple(b.shape):
                    raise RuntimeError(
                        f"onnx.export self-check: output {i} shape "
                        f"{a.shape} != {b.shape}")
                if not np.allclose(a.astype(np.float64),
                                   b.astype(np.float64),
                                   atol=atol, rtol=rtol):
                    diff = float(np.max(np.abs(a.astype(np.float64)
                                               - b.astype(np.float64))))
                    raise RuntimeError(
                        f"onnx.export self-check: output {i} max diff "
                        f"{diff} exceeds atol={atol}/rtol={rtol}")

        check(args)
        if dyn_axes:
            # the dynamic-batch claim is only honest if the graph runs
            # and matches at a batch size the trace never saw
            check([np.concatenate([a, a[:1]], axis=0) if d else a
                   for a, d in zip(args, dyn_axes)])

    onnx_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(onnx_path, "wb") as f:
        f.write(model_bytes)
    return onnx_path
