from .export import export  # noqa: F401
